"""Process wiring — entry point E1 (SURVEY.md §3).

main() → parse flags (C6) → detect backend (TPU present? else mock/null,
C7/C11) → discover() devices → start attribution watcher (C3) → registry
(C4) → HTTP server (C5) → poll loop (C2). Process-boundary crossings:
kubelet gRPC over unix socket, libtpu metrics gRPC over localhost TCP.
"""

from __future__ import annotations

import logging
import signal
import threading

from . import __version__, topology
from .config import Config
from .collectors import Collector
from .collectors.mock import MockCollector, NullCollector
from .exposition import (MetricsServer, PushgatewayPusher, RenderStats,
                         TextfileWriter)
from .poll import AttributionProvider, NullAttribution, PollLoop
from .procopen import DeviceProcessWatcher
from .registry import Registry
from .supervisor import Supervisor
from .tracing import Tracer
from .workers import PeriodicRefresher

log = logging.getLogger(__name__)


def detect_tpu(cfg: Config) -> bool:
    """Is a TPU visible on this node? Shares the production definition of
    "TPU present" — ``TpuCollector.discover`` probes the accel sysfs class
    first and, when that is absent (TPU VM variants without it), falls back
    to one bounded libtpu discovery RPC per configured port (the round-1
    hole: sysfs-less TPU VMs silently landed on the null backend)."""
    probe = _tpu_collector(cfg)
    try:
        return bool(probe.discover())
    finally:
        probe.close()


def build_collector(cfg: Config) -> Collector:
    if cfg.backend == "mock":
        return MockCollector(num_devices=cfg.mock_devices)
    if cfg.backend == "null":
        return NullCollector()
    if cfg.backend == "tpu":
        return _tpu_collector(cfg)
    if cfg.backend == "gpu":
        return _gpu_collector(cfg)
    # auto: TPU when present, else sysfs-exposed GPUs (C12 single-binary
    # mixed clusters), else a schema-valid null exporter (BASELINE.json
    # configs[0] behavior on CPU-only nodes; the daemon keeps re-probing
    # while on null — see BackendUpgradeWatcher).
    return probe_accelerator(cfg) or NullCollector()


def probe_accelerator(cfg: Config, loglevel: int = logging.WARNING
                      ) -> Collector | None:
    """One pass of the auto-backend probe order: TPU, then GPU, else None.
    The probe instance IS the production collector when devices are found —
    probing and serving must never disagree about what "present" means.
    ``loglevel`` lets the periodic re-probe demote the expected "nothing
    here yet" outcomes to debug instead of logging a warning per cycle."""
    try:
        tpu = _tpu_collector(cfg)
        try:
            if tpu.discover():
                return tpu
        except Exception:
            tpu.close()
            raise
        tpu.close()
    except Exception as exc:
        log.log(loglevel, "TPU probe failed (%s); trying gpu backend", exc)
    try:
        gpu = _gpu_collector(cfg)
        # Require real telemetry, not mere card nodes: BMC/integrated
        # display controllers also appear under /sys/class/drm.
        if gpu.telemetry_capable():
            return gpu
    except Exception as exc:
        log.log(loglevel, "GPU probe failed (%s); falling back to null "
                "backend", exc)
    return None


def _gpu_collector(cfg: Config) -> Collector:
    from .collectors.gpu_sysfs import GpuSysfsCollector

    return GpuSysfsCollector(sysfs_root=cfg.sysfs_root)


def _tpu_collector(cfg: Config) -> Collector:
    from .collectors.composite import TpuCollector

    return TpuCollector(
        sysfs_root=cfg.sysfs_root,
        libtpu_addr=cfg.libtpu_addr,
        libtpu_ports=cfg.libtpu_ports,
        use_native=cfg.use_native,
        passthrough_unknown=cfg.passthrough_unknown == "on",
    )


def build_attribution(cfg: Config) -> AttributionProvider:
    if cfg.attribution == "off":
        return NullAttribution()
    try:
        from .attribution import build as build_attr

        return build_attr(
            mode=cfg.attribution,
            kubelet_socket=cfg.kubelet_socket,
            checkpoint_path=cfg.checkpoint_path,
            refresh_interval=cfg.attribution_interval,
        )
    except Exception as exc:
        # Attribution is an enrichment, never a reason for the DaemonSet to
        # crash-loop (SURVEY.md §5): degrade to unattributed metrics.
        log.warning("attribution unavailable (%s); exporting without pod labels",
                    exc)
        return NullAttribution()


def _backend_priority(collector) -> int:
    """auto-mode upgrade ordering: tpu beats gpu beats null. A gpu-sysfs
    latch must not suppress the TPU re-probe — a display-adjacent card
    passing the capability check would otherwise permanently mask a TPU
    whose metric service starts with the workload."""
    name = getattr(collector, "name", "")
    if name in ("tpu", "libtpu", "sysfs", "sysfs-native"):
        return 2
    if name.startswith("gpu"):
        return 1
    return 0


class BackendUpgradeWatcher(PeriodicRefresher):
    """Re-probe for a better accelerator while --backend auto latched the
    null OR gpu backend (round-2 advisor finding: the libtpu metric
    service only serves while a TPU workload is running, so a daemon
    started before the workload on a sysfs-less TPU VM would otherwise
    export nulls — or a bystander GPU — for its whole lifetime). Runs on
    the rediscovery cadence with capped backoff; upgrades apply between
    ticks, and the watcher retires once the top-priority (TPU) backend
    is in place. The first probe waits one interval: construction just
    probed milliseconds ago."""

    def __init__(self, daemon: "Daemon", interval: float) -> None:
        super().__init__(interval, "backend-upgrade",
                         first_refresh_immediately=False)
        self._daemon = daemon

    def refresh_once(self) -> None:
        current_priority = _backend_priority(self._daemon.collector)
        if current_priority >= 2:
            self._stop_event.set()  # TPU latched (e.g. via rediscovery)
            return
        try:
            new = probe_accelerator(self._daemon.cfg, loglevel=logging.DEBUG)
        except Exception:  # noqa: BLE001 - probe bug must not kill the thread
            log.debug("backend re-probe crashed", exc_info=True)
            new = None
        if new is None or _backend_priority(new) <= current_priority:
            if new is not None:
                new.close()
            # Modest backoff cap: a workload can start any time, so keep
            # probing at most ~4x the base cadence (PeriodicRefresher's
            # shared BackoffPolicy doubles the wait per failure).
            self.consecutive_failures = min(self.consecutive_failures + 1, 2)
            return
        log.info("auto backend: %s now present; upgrading from %s",
                 new.name, self._daemon.collector.name)
        self._daemon._wire_tracer(new)
        self._daemon.collector = new
        self._daemon.poll.replace_collector(new)
        if _backend_priority(new) >= 2:
            # Applied between ticks; retire this watcher (set, don't
            # join — we ARE the watcher thread).
            self._stop_event.set()


class Daemon:
    """Owns every long-lived component; start()/stop() are idempotent-ish
    and stop() tears down in reverse order."""

    def __init__(self, cfg: Config) -> None:
        self.cfg = cfg
        self.registry = Registry()
        self.render_stats = RenderStats()
        # Flight recorder (tracing.py): one instance shared by the poll
        # loop (span recording), the supervisor (breaker/health journal
        # feed), the collector's transport (per-port RPC spans) and the
        # HTTP server (/debug/ticks, /debug/trace, /debug/events).
        # --no-trace keeps the object (endpoints answer "disabled")
        # but every recording call becomes a cheap no-op. The poll loop
        # also self-exports this recorder's digest every snapshot
        # (kts_tick_phase_seconds / kts_slowest_tick_seconds,
        # fleetlens.contribute_trace_digest) — the per-node half of the
        # hub fleet lens's cross-node slow-node attribution (ISSUE 5).
        self.tracer = Tracer(enabled=cfg.trace_enabled)
        # Store-fault journal feed (ISSUE 15): every WAL-backed store's
        # disk_fault / store_recovered transitions land in the shared
        # event journal beside breaker trips and health flips.
        from . import wal as wal_mod

        wal_mod.set_journal(self.tracer)
        self.collector = build_collector(cfg)
        self._wire_tracer(self.collector)
        self.attribution = build_attribution(cfg)
        # Crash-only supervisor (supervisor.py): owns liveness/hang
        # detection and restart-with-backoff for every worker thread,
        # and aggregates circuit-breaker state from the I/O edges into
        # the kts_* self-metrics and /healthz reasons. Breakers are
        # late-bound providers: the collector's swap on a backend
        # upgrade, and the attribution source's lazy PodResources
        # client, both resolve at read time.
        self.supervisor = Supervisor(
            check_interval=max(0.1, min(1.0, cfg.interval)),
            tracer=self.tracer)
        self.supervisor.register_breaker_provider(self._collector_breakers)
        self.supervisor.register_breaker_provider(self._attribution_breakers)
        # Per-process device holders (accelerator_process_open): the lazy
        # paths_fn closes over self.poll, which exists before the watcher's
        # first refresh (start()).
        self.procwatch = (
            DeviceProcessWatcher(
                lambda: [d.device_path for d in self.poll.devices],
                proc_root=cfg.proc_root,
                refresh_interval=cfg.attribution_interval,
                max_holders=cfg.max_process_series,
            )
            if cfg.device_processes == "on"
            else None
        )
        # Burst sampler + energy accounting (ISSUE 8): the sampler
        # resolves the CURRENT collector per pass (late-bound — it
        # survives the auto-mode backend upgrade), the accountant
        # persists per-pod joules across restarts and signs the
        # /debug/energy governance digest with --energy-audit-key.
        self.burst = None
        if cfg.burst_mode != "off":
            from .burstsampler import BurstSampler

            self.burst = BurstSampler(
                lambda: self.collector,
                lambda: self.poll.devices,
                hz=cfg.burst_hz, ring=cfg.burst_ring,
                hold=cfg.burst_hold, mode=cfg.burst_mode,
                tracer=self.tracer)
        import socket as _socket

        from .energy import EnergyAccountant

        from .energy import DEFAULT_COVER_GAP

        self.energy = EnergyAccountant(
            checkpoint_path=cfg.energy_checkpoint,
            checkpoint_interval=cfg.energy_checkpoint_interval,
            audit_key=cfg.energy_audit_key,
            node=_socket.gethostname(),
            max_gap=10 * cfg.interval,
            # "Covered by burst samples" follows the configured rate:
            # at --burst-hz 5 the honest inter-sample gap is 0.2 s, and
            # the fixed default (0.1 s) would report coverage ~0 while
            # trapezoid integration was fully active — the digest would
            # understate its own fidelity to the auditor.
            cover_gap=max(DEFAULT_COVER_GAP, 4.0 / cfg.burst_hz),
        )
        # Host-signals collector (ISSUE 10): PSI/IRQ/NIC/thermal/cgroup
        # read once per tick on the poll loop's pool (never inside the
        # tick budget), exported as kts_host_* and served at
        # /debug/host. ALWAYS constructed — under --no-host-stats the
        # disabled instance keeps the endpoint up answering
        # enabled:false (the --no-trace contract). The per-pod cgroup
        # join resolves pod UIDs to pod/namespace through the existing
        # kubelet attribution mapping via device-holder processes.
        from .hoststats import HostStats

        self.hoststats = HostStats(
            proc_root=cfg.proc_root,
            sysfs_root=cfg.sysfs_root,
            cgroup_root=cfg.cgroup_root,
            pod_map=self._pod_uid_map,
            enabled=cfg.host_stats,
            # Capability-probe the optional eBPF runqueue source once
            # at startup (cheap import check; refuses gracefully —
            # /debug/host carries the reason).
            probe_ebpf=cfg.host_stats,
        )
        self.poll = PollLoop(
            self.collector,
            self.registry,
            interval=cfg.interval,
            deadline=cfg.deadline,
            attribution=self.attribution,
            topology_labels=topology.topology_labels(use_metadata=True),
            version=__version__,
            rediscovery_interval=cfg.rediscovery_interval,
            pipeline_fetch=cfg.pipeline_fetch,
            drop_labels=cfg.drop_labels,
            disabled_metrics=cfg.disabled_metrics,
            process_openers=self.procwatch.lookup if self.procwatch else None,
            push_stats=self._push_stats,
            egress_stats=self._egress_stats,
            render_stats=self.render_stats.contribute,
            health_stats=self.supervisor.contribute,
            heartbeat=self.supervisor.beater("poll"),
            tracer=self.tracer,
            burst_sampler=self.burst,
            energy=self.energy,
            host_stats=self.hoststats,
            label_value_cap=cfg.label_value_cap,
        )
        # Hung-tick watchdog threshold: same formula as healthz_max_age
        # (a few missed intervals; floor for tiny test intervals), so the
        # supervisor respawns the loop BEFORE the liveness probe would
        # kill the whole pod for the same hang.
        self.supervisor.register(
            "poll", is_alive=self.poll.thread_alive,
            restart=self.poll.respawn,
            heartbeat_timeout=max(5.0, cfg.interval * 5),
            breaker_prefixes=("libtpu",))
        self.server = MetricsServer(
            self.registry, cfg.listen_host, cfg.listen_port,
            # A few missed intervals = unhealthy (floor for tiny test
            # intervals where scheduling jitter dominates).
            healthz_max_age=max(5.0, cfg.interval * 5),
            tls_cert_file=cfg.tls_cert_file,
            tls_key_file=cfg.tls_key_file,
            tls_client_ca_file=cfg.tls_client_ca_file,
            max_concurrent_scrapes=cfg.max_concurrent_scrapes,
            auth_username=cfg.auth_username,
            auth_password_sha256=cfg.auth_password_sha256,
            render_stats=self.render_stats,
            health_provider=self.supervisor.health_report,
            trace_provider=self.tracer,
            burst_provider=self.burst,
            energy_provider=self.energy,
            host_provider=self.hoststats,
            egress_provider=self._egress_payload,
            skew_provider=self._skew_payload,
            stores_provider=self._stores_payload,
        )
        self.textfile = (
            TextfileWriter(self.registry, cfg.textfile_dir,
                           render_stats=self.render_stats)
            if cfg.textfile_enabled
            else None
        )
        self.pusher = (
            PushgatewayPusher(self.registry, cfg.pushgateway_url,
                              job=cfg.pushgateway_job,
                              render_stats=self.render_stats)
            if cfg.pushgateway_url
            else None
        )
        self.upgrade_watcher = (
            BackendUpgradeWatcher(self, cfg.rediscovery_interval)
            if cfg.backend == "auto"
            and _backend_priority(self.collector) < 2
            and cfg.rediscovery_interval > 0
            else None
        )
        self.remote_writer = None
        if cfg.remote_write_url:
            from .remote_write import RemoteWriter

            self.remote_writer = RemoteWriter(
                self.registry, cfg.remote_write_url,
                job=cfg.remote_write_job,
                min_interval=cfg.remote_write_interval,
                bearer_token_file=cfg.remote_write_bearer_token_file,
                protocol=cfg.remote_write_protocol,
                extra_labels=cfg.remote_write_extra_labels,
                render_stats=self.render_stats,
                shards=cfg.remote_write_shards,
                wal_dir=cfg.remote_write_wal_dir,
                wal_max_bytes=cfg.remote_write_wal_max_bytes,
                drain_max_per_push=cfg.remote_write_drain_max,
                tracer=self.tracer,
            )
        # Delta push to an upstream hub (ISSUE 7): each published
        # snapshot ships as a changed-series delta; the hub applies it
        # without fetch or parse and still pull-scrapes us if the
        # session goes stale. Source defaults to this node's own scrape
        # URL so the hub's fallback pull lands here.
        self.delta_pusher = None
        if cfg.hub_url:
            import socket

            from .delta import DeltaPublisher, push_headers_provider

            # Partition survival (ISSUE 13): with --hub-spill-dir, a
            # down hub link spools every published snapshot to a
            # bounded on-disk ring (drained oldest-first, rate-limited
            # on reconnect) instead of dropping it to the backoff.
            spill = None
            if cfg.hub_spill_dir:
                from .spillq import SpillQueue

                spill = SpillQueue(cfg.hub_spill_dir,
                                   max_bytes=cfg.hub_spill_max_bytes,
                                   tracer=self.tracer)
            self.delta_pusher = DeltaPublisher(
                self.registry, cfg.hub_url,
                source=cfg.hub_push_source or (
                    f"http://{socket.gethostname()}:"
                    f"{cfg.listen_port}/metrics"),
                min_interval=cfg.hub_push_interval,
                render_stats=self.render_stats,
                headers_provider=push_headers_provider(
                    cfg.hub_auth_username, cfg.hub_auth_password_file),
                ca_file=cfg.hub_ca_file,
                insecure_tls=cfg.hub_insecure_tls,
                tracer=self.tracer,
                spill=spill,
                drain_rate=cfg.hub_drain_rate,
                proto_max=cfg.hub_proto_max,
            )

    def _wire_tracer(self, collector) -> None:
        """Hand the flight recorder to a collector's transport (duck-
        typed: backends without per-port RPCs just don't record)."""
        setter = getattr(collector, "set_tracer", None)
        if callable(setter):
            setter(self.tracer)

    def _pod_uid_map(self) -> dict[str, tuple[str, str]]:
        """pod UID -> (pod, namespace) for the host collector's cgroup
        join: a device whose holder process carries a pod UID (procopen's
        cgroup parse) ties that UID to the kubelet attribution mapping's
        pod name for the same device. Best-effort dict walks over cached
        state — no RPC, safe from the host-read pool thread."""
        if self.procwatch is None:
            return {}
        out: dict[str, tuple[str, str]] = {}
        for dev in self.poll.devices:
            attribution = self.attribution.lookup(dev)
            pod = attribution.get("pod", "")
            if not pod:
                continue
            namespace = attribution.get("namespace", "")
            for _pid, _comm, pod_uid, _value in \
                    self.procwatch.lookup(dev.device_path):
                if pod_uid:
                    out.setdefault(pod_uid, (pod, namespace))
        return out

    def _collector_breakers(self):
        """Current collector's circuit breakers (late-bound: survives
        the auto-mode backend-upgrade swap)."""
        fn = getattr(self.collector, "breakers", None)
        return fn() if callable(fn) else {}

    def _attribution_breakers(self):
        """The attribution source's kubelet breaker, once it exists
        (auto mode creates the PodResources client lazily)."""
        breaker = getattr(self.attribution, "breaker", None)
        return {"kubelet": breaker} if breaker is not None else {}

    def _push_stats(self) -> dict[str, dict[str, int]]:
        """Shipping-health counters for the collector_push_* self metrics.
        Wired into the poll loop at construction; the senders are created
        after the loop, so this resolves them late (each tick)."""
        stats: dict[str, dict[str, int]] = {}
        for mode, sender in (("pushgateway", getattr(self, "pusher", None)),
                             ("remote_write",
                              getattr(self, "remote_writer", None)),
                             ("delta",
                              getattr(self, "delta_pusher", None))):
            if sender is not None:
                stats[mode] = {
                    "pushes": sender.pushes_total,
                    "failures": sender.failures_total,
                    "dropped": sender.dropped_total,
                }
                if hasattr(sender, "shed_honored_total"):
                    # Delta publishers only (ISSUE 12): hub-admission
                    # sheds this publisher honored — their own class,
                    # deliberately NOT in failures (the hub is shaping
                    # load, not failing).
                    stats[mode]["shed_honored"] = sender.shed_honored_total
                if hasattr(sender, "skew_refused_total"):
                    # Delta publishers only (ISSUE 14): pushes the
                    # upstream hub refused for wire-version skew (426)
                    # — kts_skew_refused_total on this node's own
                    # exposition, so a stuck rollout is visible from
                    # EITHER end of the link.
                    stats[mode]["skew_refused"] = sender.skew_refused_total
        return stats

    def _egress_stats(self) -> dict:
        """Spill-queue + durable remote-write status for the
        kts_spill_*/kts_remote_write_* fold and /debug/egress (ISSUE
        13). Late-bound like _push_stats — the senders are created
        after the poll loop."""
        out: dict = {}
        pusher = getattr(self, "delta_pusher", None)
        if pusher is not None:
            status = pusher.spill_status()
            if status is not None:
                out["spill"] = status
        writer = getattr(self, "remote_writer", None)
        if writer is not None:
            status_fn = getattr(writer, "egress_status", None)
            status = status_fn() if callable(status_fn) else None
            if status is not None:
                out["remote_write"] = status
        return out

    def _egress_payload(self) -> dict:
        """/debug/egress: the egress-durability picture plus per-sender
        shipping health — what `doctor --egress` summarizes. enabled
        says whether ANY durability (spill queue / durable remote
        write) is configured; sender rows appear for every configured
        sender either way (their failure counters are the 'is the link
        down' half of the triage)."""
        payload: dict = dict(self._egress_stats())
        payload["enabled"] = bool(payload)
        senders: dict = {}
        for mode, sender in (("delta", getattr(self, "delta_pusher", None)),
                             ("remote_write",
                              getattr(self, "remote_writer", None)),
                             ("pushgateway", getattr(self, "pusher", None))):
            if sender is not None:
                senders[mode] = {
                    "pushes_total": sender.pushes_total,
                    "failures_total": sender.failures_total,
                    "dropped_total": sender.dropped_total,
                    "consecutive_failures": sender.consecutive_failures,
                }
        payload["senders"] = senders
        return payload

    def _skew_payload(self) -> dict:
        """/debug/skew for a daemon (ISSUE 14): this build's version +
        wire-protocol range, the delta publisher's negotiation state
        against its upstream hub when one is configured, and any
        persisted-format files quarantined at startup — the node-side
        evidence `doctor --skew` reads."""
        from . import __version__, wal
        from .delta import PROTO_MAX, PROTO_MIN

        pusher = getattr(self, "delta_pusher", None)
        return {
            "role": "daemon",
            "build": __version__,
            "proto_min": PROTO_MIN,
            "proto_max": PROTO_MAX,
            "publisher": (pusher.skew_status()
                          if pusher is not None else None),
            "wal_quarantined": wal.quarantine_counts(),
            "wal_quarantine_events": wal.quarantine_events(),
        }

    def _stores_payload(self) -> dict:
        """/debug/stores (ISSUE 15): every disk-backed store's
        durability state machine (which store is degraded, why, what
        was lost), the accept-loop fd fence, and the supervisor's
        restarted/storm-latched thread report — what `doctor --stores`
        summarizes."""
        from . import wal

        return {
            "enabled": True,
            "role": "daemon",
            "stores": wal.store_report(),
            "accept_fence": self.server.accept_fence_status(),
            "threads": self.supervisor.restart_report(),
        }

    def start(self) -> None:
        starter = getattr(self.attribution, "start", None)
        if starter:
            starter()
        if self.procwatch:
            self.procwatch.start()
        self.server.start()
        if self.textfile:
            self.textfile.start()
        if self.pusher:
            self.pusher.start()
        if self.remote_writer:
            self.remote_writer.start()
        if self.delta_pusher:
            self.delta_pusher.start()
        if self.upgrade_watcher:
            self.upgrade_watcher.start()
        if self.burst is not None:
            self.burst.start()
        self.poll.start()
        # Liveness-only supervision for the auxiliary worker threads
        # (their loops already contain exceptions, so death is a bug —
        # the crash-only answer is a fresh thread over retained state).
        # The upgrade watcher is deliberately NOT supervised: it retires
        # itself by design once the TPU backend latches, and a restart
        # would resurrect it. Registered here, started components only;
        # the supervisor starts last so no watchdog pass can see a
        # component before its thread exists.
        for name, component in (
            ("attribution", self.attribution),
            ("pushgateway", self.pusher),
            ("remote_write", self.remote_writer),
            ("delta_push", self.delta_pusher),
            ("textfile", self.textfile),
            ("procwatch", self.procwatch),
        ):
            alive = getattr(component, "thread_alive", None)
            starter = getattr(component, "start", None)
            if component is not None and callable(alive) and callable(starter):
                # Publish-following senders beat once per loop pass
                # (ISSUE 15 coverage sweep): a sender wedged INSIDE a
                # push — a hung socket, a stuck fsync on the spill
                # drain — is detected as a hang, not only when the
                # thread dies outright. 60 s covers the worst honest
                # pass (several 10 s-timeout POSTs back to back).
                heartbeat_timeout = 0.0
                restart = starter
                if hasattr(component, "heartbeat"):
                    component.heartbeat = self.supervisor.beater(name)
                    heartbeat_timeout = 60.0
                    # Hang restarts must ABANDON the wedged thread
                    # (PublishFollower.respawn; the old one retires at
                    # its next superseded() check) — start() is
                    # deliberately a no-op on a live thread, so it
                    # cannot recover a hang.
                    restart = getattr(component, "respawn", starter)
                self.supervisor.register(
                    name, is_alive=alive, restart=restart,
                    heartbeat_timeout=heartbeat_timeout,
                    breaker_prefixes=(("kubelet",)
                                      if name == "attribution" else ()))
        if self.burst is not None:
            # The sub-tick sampler (ISSUE 15 coverage sweep): a killed
            # or wedged sampler thread silently stopped burst/energy
            # fidelity forever before this row existed.
            self.burst.heartbeat = self.supervisor.beater("burst")
            self.supervisor.register(
                "burst", is_alive=self.burst.thread_alive,
                restart=self.burst.respawn, heartbeat_timeout=30.0)
        if self.server.prewarm_enabled:
            # The render pre-warmer: a dead warmer regresses scrape p99
            # ~10x (BENCH_r06) with zero functional symptom — exactly
            # the silent-stop class the coverage sweep closes.
            self.supervisor.register(
                "render-warmer", is_alive=self.server.warm_thread_alive,
                restart=self.server.respawn_warm)
        self.supervisor.start()
        log.info(
            "kube-tpu-stats %s: backend=%s devices=%d listening on %s:%d",
            __version__, self.collector.name, len(self.poll.devices),
            self.cfg.listen_host, self.server.port,
        )

    def stop(self) -> None:
        # Supervisor first: a watchdog firing mid-teardown would respawn
        # the very threads stop() is joining.
        self.supervisor.stop()
        if self.upgrade_watcher:
            self.upgrade_watcher.stop()
        if self.burst is not None:
            self.burst.stop()
        self.poll.stop()
        # Final forced checkpoint: the last partial interval of per-pod
        # joules must survive a clean pod reschedule.
        self.energy.checkpoint(force=True)
        if self.procwatch:
            self.procwatch.stop()
        if self.textfile:
            self.textfile.stop()
        if self.pusher:
            self.pusher.stop()
        if self.remote_writer:
            self.remote_writer.stop()
        if self.delta_pusher:
            self.delta_pusher.stop()
        self.server.stop()
        stopper = getattr(self.attribution, "stop", None)
        if stopper:
            stopper()
        self.collector.close()


class JsonLogFormatter(logging.Formatter):
    """One JSON object per line, keys aligned with Cloud Logging's
    structured-log parsing (severity/message/timestamp); exception text
    folded into the message so every record stays single-line."""

    def format(self, record: logging.LogRecord) -> str:
        import json
        import time as _time

        message = record.getMessage()
        if record.exc_info:
            message += "\n" + self.formatException(record.exc_info)
        return json.dumps({
            "timestamp": _time.strftime(
                "%Y-%m-%dT%H:%M:%S", _time.gmtime(record.created)
            ) + f".{int(record.msecs):03d}Z",
            "severity": record.levelname,
            "logger": record.name,
            "message": message,
        })


def setup_logging(cfg: Config) -> None:
    level = getattr(logging, cfg.log_level.upper(), logging.INFO)
    if cfg.log_format == "json":
        handler = logging.StreamHandler()
        handler.setFormatter(JsonLogFormatter())
        logging.basicConfig(level=level, handlers=[handler])
    else:
        logging.basicConfig(
            level=level,
            format="%(asctime)s %(levelname)s %(name)s %(message)s",
        )


def run(cfg: Config) -> int:
    setup_logging(cfg)
    daemon = Daemon(cfg)
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    try:
        # Inside the try: a partial start (unwritable textfile dir, a
        # sender failing to spawn) must still tear down what DID start.
        daemon.start()
        stop.wait()
    finally:
        daemon.stop()
    return 0
