"""The production TPU backend: sysfs enumeration/environment + libtpu
runtime counters merged into one per-chip sample (C11 assembled; wired by
daemon.build_collector for --backend tpu/auto).

Failure semantics (SURVEY.md §5): the two sources degrade independently —
libtpu down => duty/HBM/ICI absent but power/temp still export; sysfs
attribute missing => that gauge absent. A chip only goes accelerator_up 0
when *neither* source yields anything.
"""

from __future__ import annotations

import logging
from typing import Mapping, Sequence

from . import Collector, CollectorError, Device, Sample
from .libtpu import LibtpuClient, LibtpuCollector
from .sysfs import SysfsCollector
from ..resilience import BreakerOpenError

log = logging.getLogger(__name__)


class TpuCollector(Collector):
    name = "tpu"
    # wait_ready accepts max_age: the poll loop may run this backend in
    # pipelined-tick mode (serve the last completed fetch, let the
    # in-flight RPC land during the inter-tick idle).
    pipelined_wait = True

    def __init__(
        self,
        sysfs_root: str = "/sys",
        libtpu_addr: str = "127.0.0.1",
        libtpu_ports: Sequence[int] = (8431,),
        use_native: bool = True,
        libtpu_client: LibtpuClient | None = None,
        rpc_timeout: float = 0.040,
        passthrough_unknown: bool = False,
    ) -> None:
        self._sysfs = SysfsCollector(sysfs_root)
        if use_native:
            from ..native import maybe_accelerate_sysfs

            self._sysfs = maybe_accelerate_sysfs(self._sysfs)
        self._libtpu = LibtpuCollector(
            libtpu_client, addr=libtpu_addr, ports=libtpu_ports,
            rpc_timeout=rpc_timeout,
            passthrough_unknown=passthrough_unknown,
        )

    def discover(self) -> Sequence[Device]:
        devices = self._sysfs.discover()
        if devices:
            return devices
        # TPU VM variants without the accel class still serve libtpu metrics.
        try:
            return self._libtpu.discover()
        except CollectorError:
            return []

    def begin_tick(self) -> None:
        self._libtpu.begin_tick()

    def wait_ready(self, timeout: float | None = None,
                   max_age: float | None = None) -> None:
        self._libtpu.wait_ready(timeout, max_age)

    def sample(self, device: Device) -> Sample:
        # sysfs first: the libtpu sample joins the tick's in-flight batched
        # RPC, so reading the local files before blocking lets the file IO
        # overlap the RPC instead of queueing behind it.
        sysfs_values: dict[str, float] = {}
        sysfs_err = None
        try:
            sysfs_values = self.read_environment(device)
        except CollectorError as exc:
            sysfs_err = exc
        self._libtpu.wait_ready()
        return self.assemble(device, sysfs_values, sysfs_err)

    # -- split-sampling fast path (poll.py): the poll workers run only the
    # -- wedge-prone file IO; the loop thread joins the fetch once via
    # -- wait_ready() and assembles every device in-memory.

    def read_environment(self, device: Device) -> dict[str, float]:
        """The blocking half: local sysfs attribute reads."""
        return dict(self._sysfs.read_environment(device))

    def assemble(self, device: Device, sysfs_values: Mapping[str, float],
                 sysfs_err: Exception | None = None,
                 runtime_ready: bool = True) -> Sample:
        """The in-memory half; call after ``wait_ready``. Failure
        semantics per the module docstring: the two sources degrade
        independently, a chip only raises when both yielded nothing.
        ``runtime_ready=False`` (this tick's fetch missed the deadline)
        skips the cache read entirely — peeking would silently serve the
        PREVIOUS tick's counters as if they were fresh."""
        values: dict[str, float] = {}
        ici: dict[str, int] = {}
        collectives = None
        raw: Mapping[str, float] = {}
        runtime_err = None
        try:
            if not runtime_ready:
                raise CollectorError("runtime fetch not ready this tick")
            runtime = self._libtpu.peek(device)
            values.update(runtime.values)
            ici.update(runtime.ici_counters)
            collectives = runtime.collective_ops
            raw = runtime.raw_values
        except CollectorError as exc:
            runtime_err = exc
        values.update(sysfs_values)
        if not values and not raw:
            raise CollectorError(
                f"chip {device.index}: libtpu: {runtime_err}; sysfs: {sysfs_err}"
            )
        if runtime_err is not None:
            log.debug("chip %d: runtime counters missing: %s",
                      device.index, runtime_err)
        if sysfs_err is not None:
            log.debug("chip %d: environment missing: %s",
                      device.index, sysfs_err)
        return Sample(
            device=device,
            values=values,
            ici_counters=ici,
            collective_ops=collectives,
            raw_values=raw,
            # Escalated staleness (resilience.py): the runtime's circuit
            # breaker is OPEN — persistently down, not a blink. The env
            # values are real, but the chip is no longer "up" and its
            # gauges ride a stale="true" label downstream. A not-ready
            # tick consults the breaker too: during an outage the
            # half-open recovery probe overruns the tick budget, and
            # that tick must stay stale, not flap the chip back to up.
            stale=(isinstance(runtime_err, BreakerOpenError)
                   or (not runtime_ready
                       and self._libtpu.device_persistently_down(device))),
        )

    def read_burst(self, device: Device) -> float | None:
        """Burst-sampler power read: power is an environment attribute,
        so the sysfs half owns it (the runtime side has no sub-tick
        surface to offer)."""
        return self._sysfs.read_burst(device)

    def breakers(self):
        """Per-port runtime breakers (supervisor/doctor resilience)."""
        return self._libtpu.breakers()

    def set_tracer(self, tracer) -> None:
        """Flight-recorder pass-through: the libtpu half owns the
        per-port RPC spans (daemon wires this; duck-typed for backends
        without it)."""
        self._libtpu.set_tracer(tracer)

    @property
    def runtime_fetch_seq(self) -> int:
        """Completed-fetch generation (poll loop: rate-feed dedup)."""
        return self._libtpu.runtime_fetch_seq

    def rpc_stats(self):
        """Runtime-transport cost figures (poll loop self-metrics +
        bench's rpc_calls_per_tick) — the libtpu half owns all RPCs."""
        return self._libtpu.rpc_stats()

    def close(self) -> None:
        self._libtpu.close()
        self._sysfs.close()
