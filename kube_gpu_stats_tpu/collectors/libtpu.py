"""libtpu runtime-metrics gRPC client (component C11, SURVEY.md §2).

Talks to the runtime's metric service on localhost (ports from
``TPU_RUNTIME_METRICS_PORTS``; one process per port on multi-process
runtimes — all are queried and merged by chip id). The proto surface lives
entirely in :mod:`..proto.tpumetrics`; this module owns transport, deadlines
and the per-tick batch cache.

Transport design for the 50 ms p50 budget (SURVEY.md §3 E2): the service
returns *every* chip's value for a metric in one RPC, so the collector
fetches all metric families once per tick in :meth:`begin_tick` — RPCs
fanned out across metric names and ports in parallel with a hard deadline —
and ``sample`` is then a dict lookup. A wedged runtime costs one tick's
cache refresh, not one hang per chip.
"""

from __future__ import annotations

import concurrent.futures
import logging
import threading
from typing import Mapping, Sequence

import grpc

from . import Collector, CollectorError, Device, Sample
from .. import schema, topology
from ..proto import tpumetrics

log = logging.getLogger(__name__)

# schema family <- runtime metric name
_VALUE_MAP: Mapping[str, str] = {
    tpumetrics.DUTY_CYCLE: schema.DUTY_CYCLE.name,
    tpumetrics.TC_UTIL: schema.TENSORCORE_UTIL.name,
    tpumetrics.HBM_USED: schema.MEMORY_USED.name,
    tpumetrics.HBM_TOTAL: schema.MEMORY_TOTAL.name,
}


class LibtpuClient:
    """One channel per runtime-metrics port; bytes-level unary calls. Ports
    are queried in parallel (multi-process runtimes serve disjoint chip
    sets per port; one wedged process must cost one rpc_timeout, not N)."""

    def __init__(self, addr: str = "127.0.0.1",
                 ports: Sequence[int] = (8431,),
                 rpc_timeout: float = 0.040) -> None:
        self._rpc_timeout = rpc_timeout
        self._methods = []
        self._channels = []
        self._port_pool = (
            concurrent.futures.ThreadPoolExecutor(
                max_workers=len(ports), thread_name_prefix="libtpu-port"
            )
            if len(ports) > 1
            else None
        )
        for port in ports:
            channel = grpc.insecure_channel(
                f"{addr}:{port}",
                options=[
                    ("grpc.enable_http_proxy", 0),
                    # A restarted libtpu must be repolled within ~a tick, not
                    # after gRPC's default 1s+ exponential reconnect backoff
                    # (SURVEY.md §5 elastic recovery at 1 Hz).
                    ("grpc.initial_reconnect_backoff_ms", 100),
                    ("grpc.min_reconnect_backoff_ms", 100),
                    ("grpc.max_reconnect_backoff_ms", 1000),
                ],
            )
            self._channels.append(channel)
            self._methods.append(
                channel.unary_unary(
                    tpumetrics.METHOD,
                    request_serializer=lambda b: b,
                    response_deserializer=lambda b: b,
                )
            )

    def _call_one(self, method, request: bytes) -> list[tpumetrics.MetricSample]:
        raw = method(request, timeout=self._rpc_timeout)
        return tpumetrics.decode_response(raw)

    def get_metric(self, metric_name: str) -> list[tpumetrics.MetricSample]:
        """Fetch one metric family from every port in parallel, merged.
        Raises CollectorError (with .status_code when the failure was a
        gRPC status) only if every port failed."""
        request = tpumetrics.encode_request(metric_name)
        samples: list[tpumetrics.MetricSample] = []
        errors: list[Exception] = []
        if self._port_pool is not None:
            outcomes = self._port_pool.map(
                lambda m: self._safe_call(m, request), self._methods
            )
        else:
            outcomes = (self._safe_call(m, request) for m in self._methods)
        for result, error in outcomes:
            if error is not None:
                errors.append(error)
            else:
                samples.extend(result)
        if errors and not samples:
            first = errors[0]
            exc = CollectorError(
                f"libtpu metric {metric_name!r} unavailable: {first}"
            )
            exc.status_code = (
                first.code() if isinstance(first, grpc.Call) else None
            )
            raise exc
        return samples

    def _safe_call(self, method, request: bytes):
        try:
            return self._call_one(method, request), None
        except (grpc.RpcError, ValueError) as exc:
            # RpcError: transport/deadline; ValueError: undecodable
            # response bytes (runtime speaking a different schema).
            return None, exc

    def close(self) -> None:
        if self._port_pool is not None:
            self._port_pool.shutdown(wait=False, cancel_futures=True)
        for channel in self._channels:
            channel.close()


class LibtpuCollector(Collector):
    """Runtime counters only (duty cycle, HBM, ICI, collectives). Composite
    with sysfs environmental reads via :mod:`.composite` for the full
    per-chip sample."""

    name = "libtpu"

    def __init__(self, client: LibtpuClient | None = None, *,
                 addr: str = "127.0.0.1", ports: Sequence[int] = (8431,),
                 accel_type: str | None = None,
                 rpc_timeout: float = 0.040) -> None:
        self._client = client or LibtpuClient(addr, ports, rpc_timeout)
        self._accel_type = accel_type if accel_type is not None else topology.accel_type()
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=len(tpumetrics.ALL_METRICS), thread_name_prefix="libtpu-rpc"
        )
        self._lock = threading.Lock()
        self._cache: dict[int, dict] = {}
        self._cache_error: CollectorError | None = CollectorError(
            "no libtpu fetch has completed yet"
        )
        # Tri-state: None = unknown, True/False = whether the runtime
        # answers the empty-selector "all metrics" request. One RPC per tick
        # beats a per-metric fan-out by ~5 round trips; older runtimes that
        # reject the batched form fall back permanently.
        self._batched: bool | None = None

    # -- discovery ----------------------------------------------------------

    def discover(self) -> Sequence[Device]:
        """Devices are whatever chips the runtime reports HBM capacity for.
        (When composed with sysfs, the sysfs enumeration wins and this is
        unused.)"""
        samples = self._client.get_metric(tpumetrics.HBM_TOTAL)
        return [
            Device(
                index=s.device_id,
                device_id=str(s.device_id),
                device_path=f"/dev/accel{s.device_id}",
                accel_type=self._accel_type,
            )
            for s in sorted(samples, key=lambda s: s.device_id)
        ]

    # -- hot path ------------------------------------------------------------

    def begin_tick(self) -> None:
        cache: dict[int, dict] = {}
        first_error: CollectorError | None = None

        def ingest(sample: tpumetrics.MetricSample) -> None:
            entry = cache.setdefault(
                sample.device_id,
                {"values": {}, "ici": {}, "collectives": None},
            )
            if sample.name == tpumetrics.ICI_TRAFFIC:
                entry["ici"][sample.link or "link0"] = int(sample.value)
            elif sample.name == tpumetrics.COLLECTIVES:
                entry["collectives"] = int(sample.value)
            elif sample.name in _VALUE_MAP:
                entry["values"][_VALUE_MAP[sample.name]] = float(sample.value)
            # Unknown names: runtime newer than our pin — ignore.

        _REJECTED = (
            grpc.StatusCode.UNIMPLEMENTED,
            grpc.StatusCode.INVALID_ARGUMENT,
            grpc.StatusCode.NOT_FOUND,
        )
        if self._batched is not False:
            try:
                for s in self._client.get_metric(""):
                    ingest(s)
                if cache:
                    self._batched = True
            except CollectorError as exc:
                if getattr(exc, "status_code", None) in _REJECTED:
                    # The runtime answered and rejected the empty selector:
                    # a capability gap — switch modes permanently.
                    self._batched = False
                    log.info("libtpu empty-selector fetch unsupported (%s); "
                             "using per-metric requests", exc)
                else:
                    # Transport failure / outage (runtime not up yet,
                    # deadline, garbled): report it but keep probing the
                    # batched path once the runtime returns.
                    first_error = exc
        if self._batched is False and first_error is None:
            futures = {
                name: self._pool.submit(self._client.get_metric, name)
                for name in tpumetrics.ALL_METRICS
            }
            for name, future in futures.items():
                try:
                    for s in future.result():
                        ingest(s)
                except CollectorError as exc:
                    # Partial data is fine (e.g. a runtime build without ICI
                    # counters); a fully-failed fetch poisons the tick below.
                    first_error = first_error or exc
                    log.debug("libtpu fetch of %s failed: %s", name, exc)
        with self._lock:
            if cache:
                self._cache = cache
                self._cache_error = None
            else:
                self._cache = {}
                self._cache_error = first_error or CollectorError(
                    "libtpu returned no samples"
                )

    def sample(self, device: Device) -> Sample:
        with self._lock:
            error = self._cache_error
            entry = self._cache.get(device.index)
        if error is not None:
            raise error
        if entry is None:
            raise CollectorError(
                f"libtpu reported no metrics for chip {device.index}"
            )
        return Sample(
            device=device,
            values=dict(entry["values"]),
            ici_counters=dict(entry["ici"]),
            collective_ops=entry["collectives"],
        )

    def close(self) -> None:
        self._pool.shutdown(wait=False, cancel_futures=True)
        self._client.close()
