"""libtpu runtime-metrics gRPC client (component C11, SURVEY.md §2).

Talks to the runtime's metric service on localhost (ports from
``TPU_RUNTIME_METRICS_PORTS``; one process per port on multi-process
runtimes — all are queried and merged by chip id). The proto surface lives
entirely in :mod:`..proto.tpumetrics`; this module owns transport, deadlines
and the per-tick batch cache.

Transport design for the 50 ms p50 budget (SURVEY.md §3 E2): the service
returns *every* chip's value for a metric in one RPC, so the collector
fetches all metric families once per tick in :meth:`begin_tick` — RPCs
fanned out across metric names and ports in parallel with a hard deadline —
and ``sample`` is then a dict lookup. A wedged runtime costs one tick's
cache refresh, not one hang per chip.
"""

from __future__ import annotations

import concurrent.futures
import functools
import logging
import math
import threading
import time
from typing import Mapping, NamedTuple, Sequence

import grpc

from . import Collector, CollectorError, Device, Sample
from .. import schema, topology
from ..proto import tpumetrics
from ..resilience import BreakerOpenError, CircuitBreaker, HALF_OPEN, OPEN

log = logging.getLogger(__name__)


class RuntimeBreakerOpen(CollectorError, BreakerOpenError):
    """Every libtpu port's circuit breaker is open: the runtime is
    persistently down, not blinking. The composite collector maps this
    to a STALE sample (accelerator_up 0, env gauges labeled
    stale="true") instead of the transient env-only degradation."""

# gRPC statuses that are a capability answer ("this runtime doesn't have
# that") rather than an outage. Load-bearing in two places: the collector's
# per-family/batched-mode latching below, and doctor's healthy-vs-
# unreachable port classification — keep them agreeing.
REJECTED_STATUS = (
    grpc.StatusCode.UNIMPLEMENTED,
    grpc.StatusCode.INVALID_ARGUMENT,
    grpc.StatusCode.NOT_FOUND,
)

# schema value key <- runtime metric name. Percentile families map to
# schema value keys ("family:pXX") that the snapshot builder expands into
# the percentile label — the same data-driven table serves the Python and
# fused-native ingests (native/__init__.py configures _wirefast from it).
_VALUE_MAP: Mapping[str, str] = {
    tpumetrics.DUTY_CYCLE: schema.DUTY_CYCLE.name,
    tpumetrics.TC_UTIL: schema.TENSORCORE_UTIL.name,
    tpumetrics.HBM_USED: schema.MEMORY_USED.name,
    tpumetrics.HBM_TOTAL: schema.MEMORY_TOTAL.name,
    tpumetrics.HBM_BW_UTIL: schema.MEMORY_BANDWIDTH_UTIL.name,
    tpumetrics.UPTIME: schema.UPTIME.name,
    tpumetrics.DCN_LATENCY_P50: schema.dcn_value_key("p50"),
    tpumetrics.DCN_LATENCY_P90: schema.dcn_value_key("p90"),
    tpumetrics.DCN_LATENCY_P99: schema.dcn_value_key("p99"),
}


def _ingest_sample(sample: tpumetrics.MetricSample, cache: dict[int, dict],
                   passthrough: bool = False) -> None:
    """Fold one decoded metric into the per-device cache (the pure-Python
    reference for the fused native ingest — tests/test_wirefast.py pins the
    two paths byte-equivalent). Unknown names (runtime newer than our pin)
    are dropped BEFORE the entry is created: a device that only ever
    reports unknown metrics must not materialize as a phantom chip.

    ``passthrough`` (--passthrough-unknown) reverses that drop: unknown
    finite scalars land in the entry's ``raw`` dict — and an unknown-only
    device DOES materialize, which is the point of the mode (a runtime
    speaking a different name surface still yields data, not an empty
    exporter)."""
    name = sample.name
    if (name != tpumetrics.ICI_TRAFFIC and name != tpumetrics.COLLECTIVES
            and name not in _VALUE_MAP):
        if not passthrough or not name:
            return
        value = float(sample.value)
        if math.isnan(value) or math.isinf(value):
            return
        entry = cache.setdefault(
            sample.device_id, {"values": {}, "ici": {}, "collectives": None}
        )
        # Keyed by (family, link): an alien per-link family (ICI-style)
        # must not collapse to whichever link decoded last.
        entry.setdefault("raw", {})[(name, sample.link or "")] = value
        return
    entry = cache.setdefault(
        sample.device_id, {"values": {}, "ici": {}, "collectives": None}
    )
    if name == tpumetrics.ICI_TRAFFIC:
        entry["ici"][sample.link or "link0"] = int(sample.value)
    elif name == tpumetrics.COLLECTIVES:
        entry["collectives"] = int(sample.value)
    else:
        entry["values"][_VALUE_MAP[name]] = float(sample.value)


class IngestReport(NamedTuple):
    """What one response's ingest saw, for the caller's diagnostics:
    ``dialect`` feeds LibtpuClient.note_dialect (AMBIGUOUS = discarded
    unresolved); ``unknown`` counts payloads whose family name is outside
    the pinned surface (they fold nothing — a runtime speaking different
    names would otherwise present as a clean, green, empty exporter);
    ``unknown_names`` carries the actual names where the decode path had
    them (Python; the native fast path reports only the count)."""

    dialect: str
    unknown: int = 0
    unknown_names: tuple[str, ...] = ()


def ingest_response_py(raw: bytes, cache: dict[int, dict],
                       assume: str | None = None,
                       passthrough: bool = False) -> IngestReport:
    """Decode a MetricResponse and ingest every metric (Python fallback for
    the native _wirefast.ingest). All-or-nothing: staged into a scratch
    dict so an ingest-time error (e.g. int(NaN) on a counter metric) can't
    publish the response's leading metrics — same containment as the fused
    native wrapper. ``assume`` is the port's latched dialect (resolves
    structurally ambiguous name-only responses — see
    tpumetrics.decode_response_ex). ``passthrough`` additionally folds
    unknown families into per-device ``raw`` dicts (still reported as
    unknown for visibility)."""
    staged: dict[int, dict] = {}
    samples, dialect = tpumetrics.decode_response_ex(raw, assume)
    unknown_names: list[str] = []
    for s in samples:
        name = s.name
        if (name and name != tpumetrics.ICI_TRAFFIC
                and name != tpumetrics.COLLECTIVES
                and name not in _VALUE_MAP):
            unknown_names.append(name)
        _ingest_sample(s, staged, passthrough)
    _merge_cache(staged, cache)
    return IngestReport(dialect, len(unknown_names), tuple(unknown_names))


def _merge_cache(src: dict[int, dict], dst: dict[int, dict]) -> None:
    """Fold one response's per-device entries into the tick cache with the
    same semantics as repeated _ingest_sample calls across ports."""
    for dev, entry in src.items():
        existing = dst.get(dev)
        if existing is None:
            dst[dev] = entry
        else:
            existing["values"].update(entry["values"])
            existing["ici"].update(entry["ici"])
            if entry["collectives"] is not None:
                existing["collectives"] = entry["collectives"]
            raw = entry.get("raw")
            if raw:
                existing.setdefault("raw", {}).update(raw)


def _make_fused_ingest(wirefast):
    def ingest_response_native(raw: bytes, cache: dict[int, dict],
                               assume: str | None = None) -> IngestReport:
        # Stage into a scratch dict so a ValueError mid-response can't
        # publish a corrupt response's leading metrics (all-or-nothing,
        # matching the Python path's decode-then-ingest order).
        staged: dict[int, dict] = {}
        _n, dcode, unknown = wirefast.ingest(raw, staged)
        if dcode == 2:
            # Ambiguous: the C scan folded nothing. Delegate the whole
            # resolution contract (assume, staging, dialect return) to the
            # Python path — a cold branch that only runs on name-only
            # responses, which carry at most a handful of samples.
            return ingest_response_py(raw, cache, assume)
        _merge_cache(staged, cache)
        # Names stay in C (no per-payload allocation on the hot path);
        # the count alone triggers the collector's one-time warning, and
        # doctor's Python decode supplies the names on demand.
        return IngestReport(
            tpumetrics.FLAT if dcode == 0 else tpumetrics.NESTED, unknown)

    return ingest_response_native


def _load_wirefast():
    from .. import native

    try:
        wirefast = native.load_wirefast()
    except Exception:  # pragma: no cover - defensive: a broken build must
        return None    # degrade to Python, never break collection
    return None if wirefast is None else _make_fused_ingest(wirefast)


class LibtpuClient:
    """One channel per runtime-metrics port; bytes-level unary calls. Ports
    are queried in parallel (multi-process runtimes serve disjoint chip
    sets per port; one wedged process must cost one rpc_timeout, not N)."""

    # Deadline for a breaker's half-open recovery probe: must cover a
    # full TCP+HTTP/2 (re)connect, not just an answer on a warm channel.
    PROBE_RPC_TIMEOUT = 0.5

    def __init__(self, addr: str = "127.0.0.1",
                 ports: Sequence[int] = (8431,),
                 rpc_timeout: float = 0.040,
                 breaker_recovery_time: float = 1.0,
                 breaker_failure_threshold: int = 3,
                 breaker_min_span: float = 2.0) -> None:
        self._rpc_timeout = rpc_timeout
        self.ports = tuple(ports)
        # Flight recorder (tracing.Tracer), set via the collectors'
        # set_tracer chain: each port's RPC wave records an aux span
        # carrying the port number — the "which port" half of a slow
        # tick's post-mortem. None = no recording.
        self.tracer = None
        # RPCs actually issued (breaker-refused calls don't count): the
        # transport-cost figure behind bench's rpc_calls_per_tick. A
        # plain int — written on the fetch thread, read anywhere
        # (GIL-atomic), monotone.
        self.rpc_calls_total = 0
        # Per-port circuit breakers at the transport layer: a port that
        # keeps failing is refused fast (no RPC, no rpc_timeout spent on
        # it) until the recovery probe; capability answers
        # (UNIMPLEMENTED/NOT_FOUND/INVALID_ARGUMENT) count as SUCCESS —
        # the port is answering, it just lacks the family. Recovery is
        # ~one tick so a restarted runtime is repolled within two ticks
        # (SURVEY.md §5 elastic recovery at 1 Hz). The failure streak
        # must also SPAN ~two ticks (min span): doctor's back-to-back
        # diagnostic ticks, or a per-metric fan-out racking up one
        # failure per family in one tick, must not read as a persistent
        # outage.
        self.breakers: dict[int, CircuitBreaker] = {
            port: CircuitBreaker(
                f"libtpu:{port}",
                failure_threshold=breaker_failure_threshold,
                recovery_time=breaker_recovery_time,
                min_failure_span=breaker_min_span)
            for port in ports
        }
        # port -> tpumetrics.FLAT/NESTED, latched on the first successfully
        # scanned response from that port (a runtime never switches
        # dialects mid-life; doctor and logs report this for diagnosis).
        self.port_dialects: dict[int, str] = {}
        # Ports already warned about discarding an ambiguous response —
        # the drop is per-tick, the log line is once per port.
        self._ambiguous_warned: set[int] = set()
        self._methods = []
        self._channels = []
        self._port_pool = (
            concurrent.futures.ThreadPoolExecutor(
                max_workers=len(ports), thread_name_prefix="libtpu-port"
            )
            if len(ports) > 1
            else None
        )
        for port in ports:
            channel = grpc.insecure_channel(
                f"{addr}:{port}",
                options=[
                    ("grpc.enable_http_proxy", 0),
                    # A restarted libtpu must be repolled within ~a tick, not
                    # after gRPC's default 1s+ exponential reconnect backoff
                    # (SURVEY.md §5 elastic recovery at 1 Hz).
                    ("grpc.initial_reconnect_backoff_ms", 100),
                    ("grpc.min_reconnect_backoff_ms", 100),
                    ("grpc.max_reconnect_backoff_ms", 1000),
                ],
            )
            self._channels.append(channel)
            self._methods.append(
                channel.unary_unary(
                    tpumetrics.METHOD,
                    request_serializer=lambda b: b,
                    response_deserializer=lambda b: b,
                )
            )

    @staticmethod
    def all_failed_error(metric_name: str,
                         errors: list[Exception]) -> CollectorError:
        """The every-port-failed CollectorError for one family, carrying
        the per-port gRPC statuses (None for decode failures): capability
        latching must see EVERY port answer "don't have it" — a transient
        outage on one port mixed with UNIMPLEMENTED on another is not a
        capability answer."""
        first = errors[0]
        exc = CollectorError(
            f"libtpu metric {metric_name!r} unavailable: {first}"
        )
        exc.status_code = (
            first.code() if isinstance(first, grpc.Call) else None
        )
        exc.status_codes = tuple(
            e.code() if isinstance(e, grpc.Call) else None for e in errors
        )
        return exc

    @staticmethod
    def _raise_all_failed(metric_name: str, errors: list[Exception]) -> None:
        raise LibtpuClient.all_failed_error(metric_name, errors)

    def _fan_out(self, request: bytes) -> list[tuple[bytes | None, Exception | None]]:
        """Issue the request to every port in parallel (one wedged process
        must cost one rpc_timeout, not N); per-port (response, error).
        Results are in ``self.ports`` order. Dialect latching happens in
        the decode/ingest paths via :meth:`note_dialect` — they run the
        structural scan anyway, so no second pre-pass here.

        Each port's circuit breaker gates its RPC: an open breaker
        refuses fast with :class:`~..resilience.BreakerOpenError` (no
        rpc_timeout spent on a known-dead port; the per-metric fan-out
        used to pay ~N timeouts per tick against a dead process).
        Transport outcomes feed the breaker; capability-rejection
        statuses count as success — the port IS answering."""

        def call(pair):
            port, method = pair
            breaker = self.breakers[port]
            if not breaker.allow():
                return None, BreakerOpenError(
                    f"libtpu port {port} circuit open "
                    f"({breaker.describe()})")
            tracer = self.tracer
            start_ns = tracer.clock_ns() if tracer is not None else 0
            timeout = self._rpc_timeout
            wait_for_ready = False
            if breaker.state == HALF_OPEN:
                # Recovery probe: the channel's connection is torn down
                # after an outage, and re-establishing it takes longer
                # than the 40 ms hot-path deadline — a probe failing on
                # its own deadline would re-open the breaker forever.
                # Probes run off the tick's critical path (the batched
                # fetch is async; the tick degrades either way), so give
                # the probe a connection-sized deadline and let gRPC
                # wait for the channel instead of failing fast.
                timeout = max(timeout, self.PROBE_RPC_TIMEOUT)
                wait_for_ready = True
            try:
                try:
                    response = method(request, timeout=timeout,
                                      wait_for_ready=wait_for_ready)
                except grpc.RpcError as exc:
                    if exc.code() in REJECTED_STATUS:
                        breaker.record_success()
                    else:
                        breaker.record_failure(exc)
                    return None, exc
                except Exception as exc:  # noqa: BLE001 - an admitted call
                    # MUST record an outcome, whatever raised — an
                    # unrecorded half-open probe would otherwise hold the
                    # probe slot until the breaker's reclaim window.
                    breaker.record_failure(exc)
                    return None, exc
                breaker.record_success()
                return response, None
            finally:
                if tracer is not None:
                    tracer.aux_span("rpc_port", start_ns, port=port)

        pairs = list(zip(self.ports, self._methods))
        if self._port_pool is not None:
            results = list(self._port_pool.map(call, pairs))
        else:
            results = [call(pair) for pair in pairs]
        # Counted AFTER the gather, on the calling thread: `call` runs on
        # port-pool workers, where an unlocked += would race away
        # increments. Breaker-refused calls issued no RPC.
        self.rpc_calls_total += sum(
            1 for _, error in results
            if not isinstance(error, BreakerOpenError))
        return results

    def breakers_by_name(self) -> dict[str, CircuitBreaker]:
        """``{"libtpu:<port>": breaker}`` for the supervisor/doctor
        resilience surfaces."""
        return {f"libtpu:{port}": breaker
                for port, breaker in self.breakers.items()}

    def all_breakers_open(self) -> bool:
        """True when every port's breaker is OPEN — the runtime is
        persistently down, not blinking (staleness escalation)."""
        return bool(self.breakers) and all(
            breaker.state == OPEN for breaker in self.breakers.values())

    def note_dialect(self, port: int, dialect: str, raw: bytes) -> None:
        """Record the dialect a port's response decoded under (callers:
        get_metric, the collector's batched ingest, doctor). Latches
        FLAT/NESTED into ``port_dialects`` — and RE-latches when later
        evidence contradicts the stored value, because a restarted
        workload may bring a different runtime build to the same port; a
        stale latch would make ambiguous resolution fabricate flat chip-0
        zeros from empty nested answers, or keep silently dropping a new
        flat runtime's idle readings. AMBIGUOUS on a non-empty response
        means an unresolved name-only answer was discarded — logged once
        per port (see warn_ambiguous)."""
        if dialect == tpumetrics.AMBIGUOUS:
            if raw:
                self.warn_ambiguous(port)
            return
        previous = self.port_dialects.get(port)
        if previous != dialect:
            if previous is not None:
                log.warning(
                    "libtpu port %d: wire dialect changed %s -> %s "
                    "(runtime restarted with a different build?); "
                    "re-latching", port, previous, dialect)
                self._ambiguous_warned.discard(port)
            self.port_dialects[port] = dialect

    def warn_ambiguous(self, port: int) -> None:
        """Log (once per port) that a non-empty response was discarded as
        structurally ambiguous. Until any response from the port carries a
        dialect marker, a zero-omitting flat runtime's idle readings are
        being dropped — the one silent data-loss mode of the dual-dialect
        design, so it must be visible (round-2 advisor finding)."""
        if port not in self._ambiguous_warned:
            self._ambiguous_warned.add(port)
            log.warning(
                "libtpu port %d: discarded a name-only response (no "
                "structural dialect evidence yet); if this runtime speaks "
                "the flat dialect with zero-omission, idle zero readings "
                "are dropped until any nonzero value latches the dialect",
                port,
            )

    def get_metric(self, metric_name: str) -> list[tpumetrics.MetricSample]:
        """Fetch one metric family from every port in parallel, merged.
        Raises CollectorError (with .status_code when the failure was a
        gRPC status) only if every port failed; an undecodable port
        (runtime speaking a different schema) counts as failed. A port's
        latched dialect resolves its ambiguous (name-only) responses."""
        samples: list[tpumetrics.MetricSample] = []
        errors: list[Exception] = []
        results = self._fan_out(tpumetrics.encode_request(metric_name))
        for port, (raw, error) in zip(self.ports, results):
            if error is not None:
                errors.append(error)
                continue
            try:
                decoded, dialect = tpumetrics.decode_response_ex(
                    raw, self.port_dialects.get(port)
                )
            except (ValueError, OverflowError) as exc:
                # OverflowError: the nested dialect converts attribute
                # values with int() (e.g. device double_attr=inf). Either
                # way this PORT is undecodable — the others still count.
                errors.append(exc)
                continue
            self.note_dialect(port, dialect, raw)
            samples.extend(decoded)
        if errors and not samples:
            self._raise_all_failed(metric_name, errors)
        return samples

    def get_many(
        self, metric_names: Sequence[str]
    ) -> dict[str, tuple[list[tpumetrics.MetricSample], list[Exception]]]:
        """Pipelined per-metric burst — the transport shape for runtimes
        that reject the batched "" selector: ONE non-blocking async RPC
        per (port, family), all issued from the calling thread before any
        is awaited, so the per-tick transport is a single burst per port
        (wall cost ≈ one RPC round trip) instead of a worker thread per
        family. Per-family results — merged samples across ports, the
        per-port error objects, dialect latching — and per-(port, family)
        breaker accounting are identical to calling :meth:`get_metric`
        once per family, so breaker semantics (per-port trip, min
        failure span absorbing a one-tick burst of failures, half-open
        granting exactly one probe RPC with the connection-sized
        deadline) are unchanged."""
        out: dict[str, tuple[list, list]] = {
            name: ([], []) for name in metric_names
        }
        pending: list[tuple[str, int, object]] = []
        tracer = self.tracer
        # One aux span per PORT for the whole burst (first issue to last
        # result), not one per family: the post-mortem question is
        # "which port was slow", and a span per (port, family) would
        # just burn the trace's span budget saying it N times.
        port_spans: dict[int, list] = {}
        for port, method in zip(self.ports, self._methods):
            breaker = self.breakers[port]
            burst_start = tracer.clock_ns() if tracer is not None else 0
            for name in metric_names:
                if not breaker.allow():
                    out[name][1].append(BreakerOpenError(
                        f"libtpu port {port} circuit open "
                        f"({breaker.describe()})"))
                    continue
                timeout = self._rpc_timeout
                wait_for_ready = False
                if breaker.state == HALF_OPEN:
                    # Recovery probe (allow() grants exactly one per
                    # half-open window; the rest of the burst is refused
                    # above): connection-sized deadline, same rationale
                    # as _fan_out's probe branch.
                    timeout = max(timeout, self.PROBE_RPC_TIMEOUT)
                    wait_for_ready = True
                try:
                    future = method.future(
                        tpumetrics.encode_request(name),
                        timeout=timeout, wait_for_ready=wait_for_ready)
                except Exception as exc:  # noqa: BLE001 - admitted call
                    # MUST record an outcome (probe-slot reclaim contract)
                    breaker.record_failure(exc)
                    out[name][1].append(exc)
                    continue
                # Counted only once .future() accepted the call — a raise
                # above issued no RPC, and the counter's contract is
                # "RPCs actually issued".
                self.rpc_calls_total += 1
                if burst_start and port not in port_spans:
                    port_spans[port] = [burst_start, 0]
                pending.append((name, port, future))
        for name, port, future in pending:
            breaker = self.breakers[port]
            try:
                raw = future.result()
            except grpc.RpcError as exc:
                if exc.code() in REJECTED_STATUS:
                    breaker.record_success()
                else:
                    breaker.record_failure(exc)
                out[name][1].append(exc)
                continue
            except Exception as exc:  # noqa: BLE001 - see above
                breaker.record_failure(exc)
                out[name][1].append(exc)
                continue
            finally:
                # Advance the port's burst-end stamp on EVERY outcome
                # (finally runs before each continue too): the span ends
                # when the port's last pending result resolved.
                span = port_spans.get(port)
                if span is not None:
                    span[1] = tracer.clock_ns()
            breaker.record_success()
            try:
                decoded, dialect = tpumetrics.decode_response_ex(
                    raw, self.port_dialects.get(port)
                )
            except (ValueError, OverflowError) as exc:
                # This PORT is undecodable for this family — the others
                # still count (same contract as get_metric).
                out[name][1].append(exc)
                continue
            self.note_dialect(port, dialect, raw)
            out[name][0].extend(decoded)
        for port, (start_ns, end_ns) in port_spans.items():
            if end_ns:
                tracer.aux_span("rpc_port", start_ns,
                                dur_ns=end_ns - start_ns, port=port)
        return out

    def get_raw_with_errors(
        self, metric_name: str
    ) -> tuple[list[tuple[int, bytes]], list[Exception]]:
        """Fetch one metric family from every port: ((port, undecoded
        response bytes) per surviving port, per-port transport errors).
        Never raises — the caller classifies each port's error (capability
        vs outage) and resolves dialect ambiguity with the port id."""
        raws: list[tuple[int, bytes]] = []
        errors: list[Exception] = []
        results = self._fan_out(tpumetrics.encode_request(metric_name))
        for port, (raw, error) in zip(self.ports, results):
            if error is not None:
                errors.append(error)
            else:
                raws.append((port, raw))
        return raws, errors

    def close(self) -> None:
        if self._port_pool is not None:
            self._port_pool.shutdown(wait=False, cancel_futures=True)
        for channel in self._channels:
            channel.close()


class LibtpuCollector(Collector):
    """Runtime counters only (duty cycle, HBM, ICI, collectives). Composite
    with sysfs environmental reads via :mod:`.composite` for the full
    per-chip sample."""

    name = "libtpu"

    def __init__(self, client: LibtpuClient | None = None, *,
                 addr: str = "127.0.0.1", ports: Sequence[int] = (8431,),
                 accel_type: str | None = None,
                 rpc_timeout: float = 0.040,
                 passthrough_unknown: bool = False) -> None:
        self._client = client or LibtpuClient(addr, ports, rpc_timeout)
        self._accel_type = accel_type if accel_type is not None else topology.accel_type()
        # Single-worker executor for the per-tick batched fetch: begin_tick
        # dispatches here and returns immediately so the poll loop's sysfs
        # fan-out overlaps the RPC flight time instead of queueing behind it
        # (SURVEY.md §3 E2 — the RPC round trip dominates the tick; anything
        # serialized after it is pure added latency).
        self._fetch_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="libtpu-fetch"
        )
        self._inflight: concurrent.futures.Future | None = None
        # Fallback fan-out pool for duck-typed clients without get_many
        # (_fetch_per_metric); never created for the real transport.
        self._per_metric_pool: concurrent.futures.ThreadPoolExecutor | None = None
        # Fused native decode+ingest when built (native/wirefast.cc); the
        # pure-Python path is the pinned-equivalent fallback. Passthrough
        # mode pins the Python path: the C scan drops unknown names by
        # design (hot-path allocation freedom), and an operator running a
        # name-surface-mismatched runtime has already traded speed for
        # visibility by turning the mode on.
        self._passthrough = passthrough_unknown
        if passthrough_unknown:
            self._ingest_response = functools.partial(
                ingest_response_py, passthrough=True)
        else:
            self._ingest_response = _load_wirefast() or ingest_response_py
        self._lock = threading.Lock()
        self._cache: dict[int, dict] = {}
        # Last-known port -> device-id set from the batched fetch (empty
        # for per-metric-only runtimes, which carry no port attribution):
        # lets staleness escalate per DEVICE — "the port serving this
        # chip is open" — instead of only when every port is down.
        self._port_devices: dict[int, set[int]] = {}
        self._cache_error: CollectorError | None = CollectorError(
            "no libtpu fetch has completed yet"
        )
        # RPC-cost self-observability (rpc_stats): how many families the
        # last completed batched fetch carried in its one-RPC-per-port
        # form (0 = per-metric burst fallback), and how many RPCs the
        # last fetch issued in total.
        self._last_batched_families = 0
        self._last_tick_rpcs = 0
        # Monotonic completion time of the last finished refresh (0 =
        # never): wait_ready's pipelined path serves any outcome younger
        # than its max_age without joining the in-flight fetch. A plain
        # float — written under the lock with the outcome it stamps,
        # read lock-free (GIL-atomic; a racy read at worst blocks once).
        self._last_refresh_done = 0.0
        # Completed-refresh generation (0 = never; failed outcomes count
        # — they publish a fresh cache_error). The poll loop keys its
        # ICI rate-baseline feeds on this: a pipelined tick re-serving
        # the SAME completed fetch must not feed the rate tracker a
        # duplicate observation (zero-rate sample now, inflated spike
        # when the genuinely new counters finally land).
        self._refresh_seq = 0
        # Tri-state: None = unknown, True/False = whether the runtime
        # answers the empty-selector "all metrics" request. One RPC per tick
        # beats a per-metric fan-out by ~5 round trips; older runtimes that
        # reject the batched form fall back permanently.
        self._batched: bool | None = None
        # Per-metric mode: families every port rejected with a capability
        # status (UNIMPLEMENTED/NOT_FOUND/INVALID_ARGUMENT — e.g. megascale
        # metrics on a single-slice runtime). Latched like _batched so an
        # old runtime costs the failing round trips once, not every tick.
        self._unsupported: set[str] = set()
        # port -> cumulative unknown-family payload count (families the
        # runtime serves that are outside our pinned name surface; the
        # data is dropped but the drop must be visible — round-2 verdict
        # item 6). Warned once per port.
        self.unknown_family_samples: dict[int, int] = {}
        self._unknown_warned: set[int] = set()

    def _note_unknown(self, port: int, report: IngestReport) -> None:
        """Count + warn (once per port) about families outside the pinned
        name surface. A real runtime serving different metric names used
        to yield a clean, green, EMPTY exporter with nothing to diagnose
        from; the warning and the doctor row are that diagnostic."""
        self.unknown_family_samples[port] = (
            self.unknown_family_samples.get(port, 0) + report.unknown)
        if port in self._unknown_warned:
            return
        self._unknown_warned.add(port)
        names = ", ".join(sorted(set(report.unknown_names)))
        if self._passthrough:
            log.info(
                "libtpu port %d: %d payload(s) from metric families "
                "outside the pinned name surface are being exported as "
                "tpu_runtime_* passthrough gauges (%s)", port,
                report.unknown, names or "run doctor for the names")
            return
        log.warning(
            "libtpu port %d: %d payload(s) from metric families outside "
            "the pinned name surface were ignored this tick (%s); if the "
            "exporter is unexpectedly empty, this runtime speaks a "
            "different metric-name surface — run `kube-tpu-stats doctor` "
            "for the full list, or set --passthrough-unknown on to "
            "export them as tpu_runtime_* gauges", port, report.unknown,
            names or "run doctor for the names")

    # -- discovery ----------------------------------------------------------

    def discover(self) -> Sequence[Device]:
        """Devices are whatever chips the runtime reports HBM capacity for.
        (When composed with sysfs, the sysfs enumeration wins and this is
        unused.) In passthrough mode an alien name surface must still
        yield chips — the whole point of the mode — so when the pinned
        HBM family fails, fall back to the batched fetch and take every
        device id that reported ANY family, known or not."""
        error: CollectorError | None = None
        try:
            samples = self._client.get_metric(tpumetrics.HBM_TOTAL)
            ids = sorted({s.device_id for s in samples})
        except CollectorError as exc:
            if not self._passthrough:
                raise
            error = exc
            ids = []
        if not ids and self._passthrough:
            # Covers both failure AND empty success on the pinned family —
            # an alien runtime may answer the unknown name with a clean
            # zero-sample response rather than an error status.
            ids = sorted(self._passthrough_discover_ids())
            if not ids and error is not None:
                raise error
        return [
            Device(
                index=device_id,
                device_id=str(device_id),
                device_path=f"/dev/accel{device_id}",
                accel_type=self._accel_type,
            )
            for device_id in ids
        ]

    def _passthrough_discover_ids(self) -> set[int]:
        """Device ids from a batched fetch ingested with passthrough —
        discovery-time only, never the hot path."""
        raws, _errors = self._client.get_raw_with_errors("")
        cache: dict[int, dict] = {}
        for port, raw in raws:
            try:
                report = ingest_response_py(
                    raw, cache, self._client.port_dialects.get(port),
                    passthrough=True)
                self._client.note_dialect(port, report.dialect, raw)
            except (ValueError, OverflowError):
                continue
        return set(cache)

    # -- hot path ------------------------------------------------------------

    def begin_tick(self) -> None:
        """Kick off this tick's batched fetch without blocking. If the
        previous tick's fetch is still in flight (runtime slower than the
        interval), no new fetch is stacked — samplers will join the one
        already running; a wedged runtime costs one cache refresh, never an
        unbounded fetch queue."""
        if self._inflight is None or self._inflight.done():
            self._inflight = self._fetch_pool.submit(self._refresh)

    def wait_ready(self, timeout: float | None = None,
                   max_age: float | None = None) -> None:
        """Block until the current tick's fetch (if any) has landed in the
        cache. sample() does this implicitly; tests and probes that assert
        on post-fetch state call it explicitly.

        ``max_age`` enables the pipelined tick (ISSUE 3): when a fetch
        COMPLETED within the last ``max_age`` seconds, return immediately
        and let this tick serve that outcome (data or error — a failed
        refresh still counts as an answer) while the just-dispatched RPC
        keeps flying for the next tick. The RPC round trip then overlaps
        the inter-tick idle instead of sitting inside the tick's latency
        budget. The trade, documented in docs/OPERATIONS.md: runtime
        counters (and runtime-death detection) lag the tick by up to
        ``max_age`` (the poll loop's 2x-interval freshness fence); a
        cache older than ``max_age`` — wedged or
        slower-than-interval runtime — falls back to the blocking join
        so staleness handling engages exactly as without pipelining."""
        if max_age is not None:
            done_at = self._last_refresh_done
            if done_at and time.monotonic() - done_at <= max_age:
                return
        inflight = self._inflight
        if inflight is not None:
            inflight.result(timeout)

    def _refresh(self) -> None:
        """The actual fetch+ingest; runs on the fetch thread. Never raises —
        failures land in _cache_error for sample() to surface per device."""
        cache: dict[int, dict] = {}
        first_error: CollectorError | None = None
        try_per_metric = False
        rpc_calls_before = getattr(self._client, "rpc_calls_total", 0)
        # Distinct metric families the batched (one-RPC-per-port) path
        # actually delivered this tick — the kts_rpc_batched_families
        # gauge; stays empty in per-metric mode.
        batched_families: set[str] = set()
        # Set when every port rejected the "" selector this tick; _batched
        # only latches False if the per-metric pass then proves the runtime
        # is actually up (yields data) — a half-initialized runtime briefly
        # rejecting everything must not permanently downgrade the 1-RPC
        # batched mode to the ~N-RPC per-metric fan-out.
        batch_rejected: CollectorError | None = None

        _REJECTED = REJECTED_STATUS

        def capability_rejection(exc: CollectorError) -> bool:
            """True iff every port answered with a "don't have it" status —
            the only evidence that justifies latching a family off."""
            codes = getattr(exc, "status_codes", None) or (
                getattr(exc, "status_code", None),
            )
            return all(code in _REJECTED for code in codes)

        port_devices_seen: dict[int, set[int]] = {}
        if self._batched is not False:
            raws, port_errors = self._client.get_raw_with_errors("")
            decode_error: Exception | None = None
            for port, raw in raws:
                try:
                    # Per-port scratch, then merge: same all-or-nothing
                    # semantics, plus it records WHICH port serves which
                    # device ids — the per-device staleness escalation
                    # needs that to tell "this chip's port is open" from
                    # "some other port is open" on multi-port runtimes.
                    port_cache: dict[int, dict] = {}
                    report = self._ingest_response(
                        raw, port_cache, self._client.port_dialects.get(port)
                    )
                    self._client.note_dialect(port, report.dialect, raw)
                    if report.unknown:
                        self._note_unknown(port, report)
                    _merge_cache(port_cache, cache)
                    if port_cache:
                        port_devices_seen[port] = set(port_cache)
                        for entry in port_cache.values():
                            batched_families.update(entry["values"])
                            if entry["ici"]:
                                batched_families.add(tpumetrics.ICI_TRAFFIC)
                            if entry["collectives"] is not None:
                                batched_families.add(tpumetrics.COLLECTIVES)
                            raw = entry.get("raw")
                            if raw:
                                batched_families.update(
                                    family for family, _link in raw)
                except (ValueError, OverflowError) as exc:
                    # ValueError: different schema / garbled port;
                    # OverflowError: int(inf) on a counter metric.
                    # Either way contain it to this port — other ports
                    # may still be fine.
                    decode_error = exc
            rejecting = [
                e for e in port_errors
                if isinstance(e, grpc.Call) and e.code() in _REJECTED
            ]
            if cache:
                if rejecting:
                    # Mixed runtime versions: some port(s) served the
                    # batched selector, other(s) rejected it. The rejecting
                    # ports' chips only exist behind per-metric requests —
                    # top them up this tick, and leave _batched unlatched so
                    # both paths keep running every tick.
                    try_per_metric = True
                elif not port_errors:
                    self._batched = True
                # Ports merely down: serve what landed, keep probing "".
            elif port_errors and len(rejecting) == len(port_errors):
                # Every port rejected the selector: probe per-metric now,
                # latch only on evidence (see batch_rejected above).
                batch_rejected = CollectorError(
                    f"libtpu metric '' unavailable: {port_errors[0]}"
                )
                try_per_metric = True
            elif port_errors:
                first_error = CollectorError(
                    f"libtpu metric '' unavailable: {port_errors[0]}"
                )
                if rejecting:
                    # Reject + unreachable mix: serve what the rejecting
                    # (answering) ports have via per-metric this tick
                    # without latching either way.
                    try_per_metric = True
            elif decode_error is not None:
                first_error = CollectorError(
                    f"libtpu metric '' unavailable: {decode_error}"
                )
        if (self._batched is False and first_error is None) or try_per_metric:
            # Per-metric mode: ONE pipelined RPC burst per port (get_many
            # issues every family's async call before awaiting any), not
            # a worker thread per family — same per-family data and
            # error attribution, transport cost of a single round trip.
            names = [name for name in tpumetrics.ALL_METRICS
                     if name not in self._unsupported]
            burst = self._fetch_per_metric(names)
            unsupported_families: list[str] = []
            rejection_error: CollectorError | None = None
            for name in names:
                samples, errors = burst[name]
                if errors and not samples:
                    if len(errors) == 1 and isinstance(errors[0],
                                                       CollectorError):
                        # Duck-typed client fallback: get_metric already
                        # built the aggregate error with its per-port
                        # status attributes.
                        exc = errors[0]
                    else:
                        exc = LibtpuClient.all_failed_error(name, errors)
                    if capability_rejection(exc):
                        # Capability answer from every port, not an outage:
                        # latch candidate, and never the tick's error (the
                        # batched path treats these statuses the same way).
                        unsupported_families.append(name)
                        rejection_error = rejection_error or exc
                        continue
                    # Partial data is fine (e.g. a runtime build without ICI
                    # counters); a fully-failed fetch poisons the tick below.
                    first_error = first_error or exc
                    log.debug("libtpu fetch of %s failed: %s", name, exc)
                    continue
                try:
                    staged: dict[int, dict] = {}
                    for s in samples:
                        _ingest_sample(s, staged)
                    _merge_cache(staged, cache)
                except (ValueError, OverflowError) as exc:
                    # Bad value inside one family (int(inf)/int(NaN)):
                    # contain to that family, staged so its leading metrics
                    # aren't half-published — same contract as batched mode.
                    first_error = first_error or CollectorError(
                        f"libtpu metric {name!r} undecodable: {exc}"
                    )
                    log.debug("libtpu ingest of %s failed: %s", name, exc)
            if unsupported_families and cache:
                # Latch only when the same tick proved the runtime is up and
                # answering (some family returned data): a restarting or
                # half-initialized server that briefly rejects EVERY family
                # must stay un-latched so the next tick re-probes it all.
                self._unsupported.update(unsupported_families)
                log.info("libtpu metrics unsupported by this runtime, "
                         "not polling again: %s",
                         ", ".join(sorted(unsupported_families)))
            elif not cache:
                # Nothing landed. If the tick's only answers were capability
                # rejections, surface one of them (with its gRPC status)
                # rather than the generic "no samples" message.
                first_error = first_error or rejection_error
        if batch_rejected is not None:
            if cache:
                # The rejection was corroborated by working per-metric
                # requests in the same tick: a genuine capability gap.
                self._batched = False
                log.info("libtpu empty-selector fetch unsupported (%s); "
                         "using per-metric requests", batch_rejected)
            else:
                first_error = first_error or batch_rejected
        with self._lock:
            # Last-KNOWN port->devices map: entries for ports that failed
            # this tick are retained — remembering which chips a
            # now-dead port used to serve is exactly what the staleness
            # escalation needs.
            self._port_devices.update(port_devices_seen)
            self._last_batched_families = len(batched_families)
            self._last_tick_rpcs = (
                getattr(self._client, "rpc_calls_total", 0)
                - rpc_calls_before)
            if cache:
                self._cache = cache
                self._cache_error = None
            else:
                self._cache = {}
                self._cache_error = first_error or CollectorError(
                    "libtpu returned no samples"
                )
            self._last_refresh_done = time.monotonic()
            self._refresh_seq += 1

    def _fetch_per_metric(
        self, names: Sequence[str]
    ) -> Mapping[str, tuple[list, list]]:
        """Per-metric fetch: the client's pipelined burst when it has
        one; otherwise (duck-typed clients — tests, alternative
        transports — that only provide the sync per-family call) one
        get_metric per family in the same result shape."""
        get_many = getattr(self._client, "get_many", None)
        if get_many is not None:
            return get_many(names)

        def one(name: str) -> tuple[list, list]:
            try:
                return (list(self._client.get_metric(name)), [])
            except CollectorError as exc:
                return ([], [exc])
            except (ValueError, OverflowError) as exc:
                return ([], [exc])

        # Fan the families out on a (lazily created, reused) pool: a
        # wedged runtime must cost ~one rpc_timeout per refresh, not one
        # per family serially — in blocking mode the serial form would
        # blow the tick deadline every tick instead of degrading once.
        if len(names) > 1:
            if self._per_metric_pool is None:
                self._per_metric_pool = concurrent.futures.ThreadPoolExecutor(
                    max_workers=min(16, len(names)),
                    thread_name_prefix="libtpu-burst")
            return dict(zip(names, self._per_metric_pool.map(one, names)))
        return {name: one(name) for name in names}

    def sample(self, device: Device) -> Sample:
        inflight = self._inflight
        if inflight is not None:
            # Join the tick's fetch. Bounded by the gRPC deadline inside
            # _refresh; the poll loop's own per-device deadline also covers
            # this wait (sample runs on a pool worker).
            inflight.result()
        return self.peek(device)

    def peek(self, device: Device) -> Sample:
        """Read this device out of the tick cache WITHOUT joining the
        in-flight fetch — the split-sampling fast path calls wait_ready()
        once on the loop thread, then peeks every device in-memory
        (poll.py), instead of paying one thread-wake per device."""
        with self._lock:
            error = self._cache_error
            entry = self._cache.get(device.index)
            device_ports = [
                port for port, devices in self._port_devices.items()
                if device.index in devices
            ]
        if error is not None:
            if self._ports_open(device_ports):
                # Persistent outage of this device's port(s), not a
                # blink: escalate so the composite marks the chip STALE
                # (up 0, env gauges labeled) instead of quietly serving
                # env-only forever.
                raise RuntimeBreakerOpen(str(error))
            raise error
        if entry is None:
            if device_ports and self._ports_open(device_ports):
                # Multi-port runtime, partial outage: OTHER ports filled
                # the cache, but every port known to serve THIS chip has
                # an open breaker — per-device staleness, same contract
                # as the all-ports-down case.
                raise RuntimeBreakerOpen(
                    f"chip {device.index}: its libtpu port's circuit is "
                    f"open ({', '.join(map(str, device_ports))})")
            raise CollectorError(
                f"libtpu reported no metrics for chip {device.index}"
            )
        # The returned dicts alias the tick cache: every refresh builds a
        # brand-new cache wholesale (never mutates a published one), and
        # Sample consumers are read-only, so handing them out copy-free is
        # safe and keeps 2 dict copies × N chips off the post-RPC tail.
        return Sample(
            device=device,
            values=entry["values"],
            ici_counters=entry["ici"],
            collective_ops=entry["collectives"],
            raw_values=entry.get("raw") or {},
        )

    def _ports_open(self, device_ports: Sequence[int]) -> bool:
        """Is the runtime persistently down FOR THESE PORTS? Every named
        port's breaker OPEN; with no port attribution (per-metric-only
        runtimes never fill the map), fall back to all-ports-open."""
        breakers = self._client.breakers
        if not device_ports:
            return self._client.all_breakers_open()
        return all(breakers[port].state == OPEN
                   for port in device_ports if port in breakers)

    def device_persistently_down(self, device: Device) -> bool:
        """Is this device inside a persistent runtime outage right now —
        its port's breaker OPEN, or HALF_OPEN with the recovery probe
        still unresolved? The composite consults this for ticks whose
        degradation reason is 'fetch not ready': during an outage the
        half-open probe blocks up to PROBE_RPC_TIMEOUT, overrunning the
        50 ms tick budget — without this check those probe ticks would
        flap accelerator_up back to 1 (and drop the stale labels) once
        per recovery window for the whole outage."""
        with self._lock:
            device_ports = [
                port for port, devices in self._port_devices.items()
                if device.index in devices
            ]
        breakers = self._client.breakers
        candidates = ([breakers[port] for port in device_ports
                       if port in breakers]
                      or list(breakers.values()))
        return bool(candidates) and all(
            breaker.state in (OPEN, HALF_OPEN) for breaker in candidates)

    def breakers(self) -> Mapping[str, "CircuitBreaker"]:
        """Per-port circuit breakers (supervisor/doctor resilience)."""
        return self._client.breakers_by_name()

    def set_tracer(self, tracer) -> None:
        """Wire the flight recorder into the transport: per-port RPC
        waves record aux spans (the "which port" post-mortem evidence).
        Duck-typed clients without the attribute just don't record."""
        try:
            self._client.tracer = tracer
        except AttributeError:  # __slots__-style stand-in client
            pass

    @property
    def runtime_fetch_seq(self) -> int:
        """Generation of the last completed refresh (0 = none yet)."""
        return self._refresh_seq

    def rpc_stats(self) -> Mapping[str, int]:
        """Transport-cost self-observability: cumulative RPCs issued,
        RPCs the last fetch cost, and how many families the last batched
        fetch carried per single RPC (0 = per-metric burst fallback —
        the kts_rpc_batched_families gauge)."""
        return {
            # getattr: duck-typed clients (tests, alternative transports)
            # may not carry the counter — same guard _refresh uses.
            "rpc_calls_total": getattr(self._client, "rpc_calls_total", 0),
            "rpc_calls_last_tick": self._last_tick_rpcs,
            "batched_families": self._last_batched_families,
        }

    def close(self) -> None:
        self._fetch_pool.shutdown(wait=False, cancel_futures=True)
        if self._per_metric_pool is not None:
            self._per_metric_pool.shutdown(wait=False, cancel_futures=True)
        self._client.close()
