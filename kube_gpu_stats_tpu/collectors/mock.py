"""Mock collector (C7): schema-valid synthetic telemetry with no accelerator.

Shippable product feature for CPU-only nodes (BASELINE.json configs[0]) and
the fixture every test layer builds on (SURVEY.md §4 "fake backends"). The
reference genre does the same with a stub nvidia-smi on PATH; here it is a
first-class Collector.

Values are deterministic functions of (chip, tick) so golden tests are
byte-stable: duty cycle is a per-chip phase-shifted triangle wave, HBM a
slow ramp, ICI counters advance at a chip-dependent constant rate.
"""

from __future__ import annotations

import itertools
from typing import Sequence

from . import Collector, CollectorError, Device, Sample
from .. import schema

_HBM_TOTAL = 95 * 1024**3  # v5p-class HBM capacity, bytes
_LINKS = ("x0", "x1", "y0", "y1", "z0", "z1")  # v5p 3D-torus link names
_BURST_BASE_WATTS = 90.0  # matches sample()'s idle power floor


class MockCollector(Collector):
    name = "mock"

    def __init__(
        self,
        num_devices: int = 4,
        accel_type: str = "mock",
        fail_devices: Sequence[int] = (),
        start_tick: int = 0,
    ) -> None:
        self._num = num_devices
        self._accel_type = accel_type
        self._fail = frozenset(fail_devices)
        # Per-device tick counters so each sample advances deterministically
        # regardless of call interleaving.
        self._ticks = [itertools.count(start_tick) for _ in range(num_devices)]
        # Burst-path fake knob (ISSUE 8): the burst sampler reads this
        # instead of sysfs on mock nodes. Default is the steady base
        # draw; tests/sims install their own (device, t) -> watts to
        # script sub-tick transients the 1 Hz sample() path never sees.
        self.burst_power_fn = None  # None = flat _BURST_BASE_WATTS

    def read_burst(self, device: Device, t: float | None = None) -> float:
        """Burst-sampler power read (watts). ``t`` lets scripted
        burst_power_fn knobs key the transient off the sampler's own
        clock; the production sampler passes nothing and the default
        returns the flat base draw."""
        if self.burst_power_fn is not None:
            return float(self.burst_power_fn(device, t))
        return _BURST_BASE_WATTS

    def discover(self) -> Sequence[Device]:
        return [
            Device(
                index=i,
                device_id=str(i),
                device_path=f"/dev/accel{i}",
                accel_type=self._accel_type,
                uuid=f"mock-{i:04x}",
            )
            for i in range(self._num)
        ]

    def sample(self, device: Device) -> Sample:
        if device.index in self._fail:
            raise CollectorError(f"mock failure injected for chip {device.index}")
        tick = next(self._ticks[device.index])
        # Triangle wave 0..100 with period 60 ticks, phase-shifted per chip.
        phase = (tick + device.index * 7) % 60
        duty = (phase if phase <= 30 else 60 - phase) * (100.0 / 30.0)
        hbm_used = int(_HBM_TOTAL * (0.10 + 0.008 * ((tick + device.index) % 100)))
        values = {
            schema.DUTY_CYCLE.name: duty,
            schema.TENSORCORE_UTIL.name: duty * 0.85,
            schema.MEMORY_USED.name: float(hbm_used),
            schema.MEMORY_TOTAL.name: float(_HBM_TOTAL),
            schema.MEMORY_BANDWIDTH_UTIL.name: duty * 0.6,
            schema.POWER.name: 90.0 + duty * 2.5,
            schema.TEMPERATURE.name: 35.0 + duty * 0.3,
            schema.UPTIME.name: float(3600 + tick),
            # Synthetic multislice DCN latency: a stable spread around a
            # per-chip base so the percentile ordering p50<p90<p99 holds.
            schema.dcn_value_key("p50"): 0.0010 + 0.0001 * device.index,
            schema.dcn_value_key("p90"): 0.0030 + 0.0001 * device.index,
            schema.dcn_value_key("p99"): 0.0080 + 0.0001 * device.index,
        }
        # Cumulative link counters: constant per-link rate, distinct per chip
        # so multi-host tests can tell series apart.
        rate = 1_000_000 * (device.index + 1)
        ici = {link: (tick + 1) * rate * (li + 1) for li, link in enumerate(_LINKS)}
        return Sample(
            device=device,
            values=values,
            ici_counters=ici,
            collective_ops=(tick + 1) * 10 * (device.index + 1),
        )


class NullCollector(Collector):
    """Zero devices: exposition stays schema-valid (self-metrics only) on
    nodes with no accelerator and mock mode disabled."""

    name = "null"

    def discover(self) -> Sequence[Device]:
        return []

    def sample(self, device: Device) -> Sample:  # pragma: no cover
        raise CollectorError("null collector has no devices")
