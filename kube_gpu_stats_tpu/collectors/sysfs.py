"""``/sys/class/accel`` discovery + environmental attribute reads (part of
C11, SURVEY.md §2; [T]-tier contract — the accel class is how TPU VMs expose
chips, replacing the reference's NVML device enumeration).

Discovery enumerates ``<sysfs_root>/class/accel/accel[0-9]*``. Attribute
reads follow the Linux hwmon convention under each device
(``device/hwmon/hwmon*/power1_average`` in microwatts,
``temp1_input`` in millidegrees C), with flat-file fallbacks; every read is
optional — a missing attribute just means that gauge isn't exported for the
chip. Fixture trees under tests/ pin the parsing (SURVEY.md §4 "sysfs parser
tests against fixture trees").

When the C++ fast-path library is available it performs the batched file
reads (kube_gpu_stats_tpu/native/); this module is the always-available
pure-Python path and the single place that knows the attribute layout.
"""

from __future__ import annotations

import glob
import os
import re
from pathlib import Path
from typing import Sequence

from . import Collector, CollectorError, Device, Sample
from .. import schema, topology

_ACCEL_RE = re.compile(r"accel(\d+)$")

# Candidate relative paths per metric, tried in order. (path, scale) pairs:
# value_in_metric_units = raw * scale.
_POWER_CANDIDATES = (
    ("device/hwmon/hwmon*/power1_average", 1e-6),  # microwatts -> watts
    ("power_usage_uw", 1e-6),
)
_TEMP_CANDIDATES = (
    ("device/hwmon/hwmon*/temp1_input", 1e-3),  # millidegree C -> C
    ("temperature_mc", 1e-3),
)
_UUID_CANDIDATES = ("uuid", "device/serial_number")


def _read_scaled(accel_dir: Path, candidates) -> float | None:
    for pattern, scale in candidates:
        for path in sorted(glob.glob(str(accel_dir / pattern))):
            try:
                return float(Path(path).read_text().strip()) * scale
            except (OSError, ValueError):
                continue
    return None


def _read_text(accel_dir: Path, names) -> str:
    for name in names:
        try:
            return (accel_dir / name).read_text().strip()
        except OSError:
            continue
    return ""


class SysfsCollector(Collector):
    name = "sysfs"

    def __init__(self, sysfs_root: str | os.PathLike = "/sys",
                 accel_type: str | None = None) -> None:
        self._root = Path(sysfs_root)
        self._accel_type = accel_type if accel_type is not None else topology.accel_type()
        # Resolved power-attribute path per device for the burst path:
        # read_burst runs at 100 Hz+, where re-running the candidate
        # glob per read would dominate the sample cost. Invalidated on
        # read failure (hwmon renumbering after a driver reload).
        self._burst_paths: dict[str, tuple[str, float]] = {}

    def accel_dir(self, device: Device) -> Path:
        return self._root / "class" / "accel" / f"accel{device.index}"

    def discover(self) -> Sequence[Device]:
        devices = []
        for path in sorted(glob.glob(str(self._root / "class" / "accel" / "accel*"))):
            match = _ACCEL_RE.search(path)
            if not match:
                continue
            index = int(match.group(1))
            devices.append(
                Device(
                    index=index,
                    device_id=str(index),
                    device_path=f"/dev/accel{index}",
                    accel_type=self._accel_type,
                    uuid=_read_text(Path(path), _UUID_CANDIDATES),
                )
            )
        devices.sort(key=lambda d: d.index)
        return devices

    def read_environment(self, device: Device) -> dict[str, float]:
        """Power/temperature attribute reads; shared with the composite
        collector so layout knowledge stays in one module."""
        accel = self.accel_dir(device)
        if not accel.exists():
            raise CollectorError(f"{accel} vanished")
        values: dict[str, float] = {}
        power = _read_scaled(accel, _POWER_CANDIDATES)
        if power is not None:
            values[schema.POWER.name] = power
        temp = _read_scaled(accel, _TEMP_CANDIDATES)
        if temp is not None:
            values[schema.TEMPERATURE.name] = temp
        return values

    def read_burst(self, device: Device) -> float | None:
        """One power reading in watts for the burst sampler
        (burstsampler.py): the single hottest read in the process, so
        the candidate glob resolves once per device and the steady
        state is open/read/close on a cached path. None = no power
        attribute (the sampler just skips the device)."""
        cached = self._burst_paths.get(device.device_id)
        if cached is not None:
            path, scale = cached
            try:
                return float(Path(path).read_text().strip()) * scale
            except (OSError, ValueError):
                # hwmon renumbered / attribute vanished: re-resolve.
                del self._burst_paths[device.device_id]
        accel = self.accel_dir(device)
        for pattern, scale in _POWER_CANDIDATES:
            for path in sorted(glob.glob(str(accel / pattern))):
                try:
                    value = float(Path(path).read_text().strip()) * scale
                except (OSError, ValueError):
                    continue
                self._burst_paths[device.device_id] = (path, scale)
                return value
        return None

    def sample(self, device: Device) -> Sample:
        return Sample(device=device, values=self.read_environment(device))
