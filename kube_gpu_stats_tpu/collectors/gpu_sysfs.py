"""NVML-free GPU collector over /sys/class/drm + hwmon (extends C12).

docs/UNIFIED_SCHEMA.md's relabel recipe converges *existing* GPU exporters
onto the accelerator_* schema; this collector makes mixed clusters a
single-binary story where the kernel driver exposes telemetry through
sysfs — the amdgpu layout (gpu_busy_percent, mem_info_vram_*, hwmon
power/temp) and any driver following the same conventions. Zero NVML
symbols, preserving the BASELINE.md binary constraint: on NVIDIA nodes
without such sysfs files the collector simply discovers the cards and
exports what's readable (attribution still works via PodResources).

Layout read per card (all optional, missing => gauge omitted):

    /sys/class/drm/card<N>/device/gpu_busy_percent      -> duty cycle (%)
    /sys/class/drm/card<N>/device/mem_info_vram_used    -> memory used (B)
    /sys/class/drm/card<N>/device/mem_info_vram_total   -> memory total (B)
    /sys/class/drm/card<N>/device/hwmon/hwmon*/power1_average -> power (uW)
    /sys/class/drm/card<N>/device/hwmon/hwmon*/temp1_input    -> temp (mC)
    /sys/class/drm/card<N>/device/unique_id             -> uuid
    /sys/class/drm/card<N>/device/vendor                -> accel_type
"""

from __future__ import annotations

import glob
import re
from pathlib import Path
from typing import Sequence

from . import Collector, CollectorError, Device, Sample
from .. import schema

_CARD_RE = re.compile(r"card(\d+)$")

_VENDORS = {
    "0x1002": "gpu-amd",
    "0x10de": "gpu-nvidia",
    "0x8086": "gpu-intel",
}

# (metric, relative candidates, scale)
_ATTRIBUTES = (
    (schema.DUTY_CYCLE.name, ("device/gpu_busy_percent",), 1.0),
    (schema.MEMORY_USED.name, ("device/mem_info_vram_used",), 1.0),
    (schema.MEMORY_TOTAL.name, ("device/mem_info_vram_total",), 1.0),
    (schema.POWER.name, ("device/hwmon/hwmon*/power1_average",), 1e-6),
    (schema.TEMPERATURE.name, ("device/hwmon/hwmon*/temp1_input",), 1e-3),
)


def _read_first(card_dir: Path, patterns, scale: float) -> float | None:
    for pattern in patterns:
        for path in sorted(glob.glob(str(card_dir / pattern))):
            try:
                return float(Path(path).read_text().strip()) * scale
            except (OSError, ValueError):
                continue
    return None


class GpuSysfsCollector(Collector):
    name = "gpu-sysfs"

    def __init__(self, sysfs_root: str = "/sys") -> None:
        self._root = Path(sysfs_root)
        # Burst-path cached power attribute per card (same contract as
        # SysfsCollector.read_burst — the vestigial GPU backend grows
        # the identical hooks so the multi-backend refactor lands the
        # burst sampler once for every accelerator).
        self._burst_paths: dict[str, str] = {}

    def _card_dir(self, device: Device) -> Path:
        return self._root / "class" / "drm" / f"card{device.index}"

    def discover(self) -> Sequence[Device]:
        devices = []
        for path in sorted(glob.glob(str(self._root / "class" / "drm" / "card*"))):
            match = _CARD_RE.search(path)
            if not match:  # skips card0-DP-1 style connector nodes
                continue
            index = int(match.group(1))
            card = Path(path)
            vendor = ""
            try:
                vendor = (card / "device" / "vendor").read_text().strip().lower()
            except OSError:
                pass
            uuid = ""
            try:
                uuid = (card / "device" / "unique_id").read_text().strip()
            except OSError:
                pass
            devices.append(
                Device(
                    index=index,
                    device_id=str(index),
                    device_path=f"/dev/dri/card{index}",
                    accel_type=_VENDORS.get(vendor, "gpu"),
                    uuid=uuid,
                )
            )
        devices.sort(key=lambda d: d.index)
        return devices

    def telemetry_capable(self) -> bool:
        """True if at least one discovered card exposes a compute-telemetry
        attribute. Mere card existence is NOT enough for auto-detection: a
        BMC framebuffer or integrated display controller has a
        /sys/class/drm/card0 with none of these files, and such nodes must
        fall back to the null backend (BASELINE configs[0])."""
        for device in self.discover():
            card = self._card_dir(device)
            for _, patterns, _ in _ATTRIBUTES:
                for pattern in patterns:
                    for hit in glob.glob(str(card / pattern)):
                        # Readability, not mere existence: a file that
                        # EPERMs on read (restricted container) would
                        # latch a backend that exports nothing, while
                        # null keeps the auto re-probe loop alive.
                        try:
                            float(Path(hit).read_text().strip())
                        except (OSError, ValueError):
                            continue
                        return True
        return False

    def read_burst(self, device: Device) -> float | None:
        """Burst-sampler power read (watts), path cached per card —
        hwmon power1_average in microwatts, the same attribute
        sample() exports as accelerator_power_watts. None when the
        card exposes no power attribute."""
        path = self._burst_paths.get(device.device_id)
        if path is not None:
            try:
                return float(Path(path).read_text().strip()) * 1e-6
            except (OSError, ValueError):
                del self._burst_paths[device.device_id]
        card = self._card_dir(device)
        for hit in sorted(glob.glob(
                str(card / "device" / "hwmon" / "hwmon*"
                    / "power1_average"))):
            try:
                value = float(Path(hit).read_text().strip()) * 1e-6
            except (OSError, ValueError):
                continue
            self._burst_paths[device.device_id] = hit
            return value
        return None

    def sample(self, device: Device) -> Sample:
        card = self._card_dir(device)
        if not card.exists():
            raise CollectorError(f"{card} vanished")
        values: dict[str, float] = {}
        for metric, patterns, scale in _ATTRIBUTES:
            value = _read_first(card, patterns, scale)
            if value is not None:
                values[metric] = value
        return Sample(device=device, values=values)
