"""Device backends (layer L0, SURVEY.md §1).

The reference's L0 is an NVML/DCGM collector shelling to / linking against
nvidia-smi (SURVEY.md §2 C1). Here L0 is a small trait with three
implementations and zero NVML anywhere:

- :mod:`.mock`   — deterministic synthetic devices (C7): product feature for
                   CPU-only nodes *and* the universal test fixture.
- :mod:`.sysfs`  — ``/sys/class/accel`` enumeration + attribute reads (C11).
- :mod:`.libtpu` — libtpu runtime-metrics gRPC client (C11).
- :mod:`.composite` — merges sysfs static/environmental data with libtpu
                   runtime counters into one sample per chip.
"""

from __future__ import annotations

import abc
import dataclasses
from typing import Mapping, Sequence


@dataclasses.dataclass(frozen=True)
class Device:
    """One local accelerator chip.

    ``device_id`` is the stable node-local identity used for attribution
    joins; for TPUs this is the id the GKE device-plugin reports to kubelet
    (e.g. "0"-"3" or "/dev/accel0"-style, version dependent) — the
    attribution layer matches on several candidate forms (SURVEY.md §7
    hard part c).
    """

    index: int
    device_id: str
    device_path: str  # "/dev/accel0"
    accel_type: str  # "tpu-v5p", "mock", ...
    uuid: str = ""


@dataclasses.dataclass(frozen=True)
class Sample:
    """One poll of one device.

    ``values`` maps metric-family name (schema.py) -> value.
    ``ici_counters`` maps link name -> cumulative traffic bytes; the poll
    loop turns deltas into bandwidth gauges (C10 rate math lives OFF the
    collector so every backend gets wraparound handling for free).
    ``raw_values`` maps ``(family, link)`` pairs — the runtime-native
    family name outside the pinned schema, and its link attribute or ""
    — to values (libtpu passthrough mode, --passthrough-unknown); the
    poll loop exports them under the ``tpu_runtime_passthrough`` gauge
    with the pair as the ``family``/``link`` labels.
    """

    device: Device
    values: Mapping[str, float]
    ici_counters: Mapping[str, int] = dataclasses.field(default_factory=dict)
    collective_ops: int | None = None
    raw_values: Mapping[tuple[str, str], float] = dataclasses.field(
        default_factory=dict)
    # Persistent-degradation marker (resilience.py): the runtime side of
    # this sample is known-down (its circuit breaker is open), so what's
    # here is environment-only. The poll loop flips accelerator_up to 0
    # and labels the surviving gauges stale="true" instead of letting
    # the chip look merely "runtime-metrics-free".
    stale: bool = False


class CollectorError(RuntimeError):
    """A sample failed; the poll loop marks the device stale (never crashes —
    SURVEY.md §5 failure-detection contract for a DaemonSet)."""


class Collector(abc.ABC):
    """L0 trait: ``discover() -> [Device]``, ``sample(Device) -> Sample``."""

    name: str = "abstract"

    @abc.abstractmethod
    def discover(self) -> Sequence[Device]:
        """Enumerate local devices. Called at startup and on rediscovery —
        never on the poll hot path."""

    def begin_tick(self) -> None:
        """Called once by the poll loop before the per-device fan-out of a
        tick. Backends whose transport is naturally batched (libtpu returns
        every chip's value in one RPC) refresh a tick-scoped cache here so
        ``sample`` stays a lookup; per-device backends ignore it. Errors
        must be swallowed and surfaced per-device from ``sample``."""

    @abc.abstractmethod
    def sample(self, device: Device) -> Sample:
        """Read one device's current counters. Hot path: must be fast and
        must raise CollectorError (not crash) on backend failure."""

    def close(self) -> None:  # pragma: no cover - trivial default
        pass
