#!/usr/bin/env python
"""Driver benchmark entry point. Prints ONE JSON line:

    {"metric": ..., "value": N, "unit": "ms", "vs_baseline": N, ...}

Metric: p50 poll-tick latency over all local chips (the BASELINE.md
north-star: every per-chip TPU metric collected at 1 Hz in < 50 ms p50).
``vs_baseline`` = 50ms-budget / measured-p50, so 1.0 = exactly on budget
and larger is better.

Runs against the real TPU backend (libtpu metric service + /sys/class/accel)
when a chip is visible; otherwise runs the SURVEY.md §4 simulated-node
harness — 8 chips behind a fake libtpu gRPC server with a scripted 10 ms
RPC delay — which measures the full production collection stack (wire
decode, fan-out, rate math, snapshot build) on any machine.

``--quick`` (make bench-quick): reduced-tick simulated harness + 64-worker
hub merge only, no real-chip probing (the bounded jax probe alone can
take 90 s) — a <60 s smoke number for perf changes, not a BENCH artifact.
"""

import json
import os
import sys
import tempfile

BUDGET_MS = 50.0


def _delta_fields(line: dict, quick: bool = False) -> None:
    """Push-delta + federation figures (ISSUE 7): the root-hub warm
    refresh at 4096 simulated workers over delta ingest, the per-wave
    ingest cost, and the quiet-tick payload ratio — plus the 10k-pusher
    ingest storm (ISSUE 11: wave apply cost, ingest CPU share, and
    fleet-wide resync-storm recovery; skipped in --quick to keep the
    smoke under a minute). An extra datum — omitted on failure, never a
    bench failure."""
    from kube_gpu_stats_tpu.bench import (measure_delta_federation,
                                          measure_ingest_storm,
                                          measure_ingest_storm_procs,
                                          measure_quiet_tick_delta)

    fed = measure_delta_federation()
    if fed is not None:
        line["root_merge_4096w_p50_ms"] = fed["root_merge_p50_ms"]
        line["root_merge_4096w_cold_ms"] = fed["root_merge_cold_ms"]
        line["delta_ingest_ms_per_refresh"] = fed[
            "delta_ingest_ms_per_refresh"]
        line["delta_bytes_per_tick"] = fed["delta_bytes_per_refresh"]
        line["federation_root_series"] = fed["root_series"]
    quiet = measure_quiet_tick_delta()
    if quiet is not None:
        line["delta_quiet_tick_bytes"] = quiet["quiet_delta_bytes"]
        line["delta_full_snapshot_bytes"] = quiet["full_bytes"]
        line["delta_quiet_tick_ratio"] = quiet["ratio"]
    if quick:
        # --quick storm mode (ISSUE 17): a 2k-pusher, 2-wave in-process
        # storm — same machinery, ~15x cheaper — normalized to the
        # per-frame figure shared with the full run so the perf ledger
        # has an ingest number from smoke runs too.
        storm = measure_ingest_storm(pushers=2_000, waves=2)
        if storm is not None:
            line["delta_ingest_storm_us_per_frame"] = round(
                storm["delta_ingest_10k_ms_per_refresh"] * 1000.0
                / storm["pushers"], 2)
            line["ingest_cpu_pct"] = storm["ingest_cpu_pct"]
    else:
        storm = measure_ingest_storm()
        if storm is not None:
            line["delta_ingest_10k_ms_per_refresh"] = storm[
                "delta_ingest_10k_ms_per_refresh"]
            line["delta_ingest_storm_us_per_frame"] = round(
                storm["delta_ingest_10k_ms_per_refresh"] * 1000.0
                / storm["pushers"], 2)
            line["ingest_cpu_pct"] = storm["ingest_cpu_pct"]
            line["resync_storm_recovery_s"] = storm[
                "resync_storm_recovery_s"]
            line["resync_storm_dropped"] = storm["resync_storm_dropped"]
            line["ingest_lanes"] = storm["lanes"]
            line["ingest_native"] = storm["native"]
        # The same storm through 4 SO_REUSEPORT acceptor processes
        # (ISSUE 17 tentpole 3): real HTTP clients against the pool's
        # public port, with the per-proc counter conservation law
        # checked (acceptance pin for --ingest-procs).
        storm_mp = measure_ingest_storm_procs()
        if storm_mp is not None:
            line["delta_ingest_10k_procs4_ms_per_refresh"] = storm_mp[
                "delta_ingest_procs_ms_per_refresh"]
            line["ingest_procs"] = storm_mp["procs"]
            line["ingest_procs_conserved"] = storm_mp["conserved"]
        # Survival-layer figures (ISSUE 12): warm-restart resume rate +
        # replay wall at 2k sessions, and the shed-priority outcome of
        # a 4x-budget stampede (CI pins live in tests/test_latency.py).
        from kube_gpu_stats_tpu.bench import (measure_overload_shed,
                                              measure_warm_restart)

        warm = measure_warm_restart()
        if warm is not None:
            line["warm_restart_resumed_fraction"] = warm[
                "resumed_fraction"]
            line["warm_restart_replay_s_2k"] = warm["replay_s"]
            line["warm_restart_recovery_s_2k"] = warm["recovery_s"]
            line["warm_restart_dropped"] = warm["dropped"]
        shed = measure_overload_shed()
        if shed is not None:
            line["shed_delta_429"] = shed["delta_shed"]
            line["shed_full_refused"] = shed["full_refused"]
            line["shed_sources_served_fraction"] = shed[
                "sources_served_fraction"]


def _egress_fields(line: dict) -> None:
    """Partition-survival egress figures (ISSUE 13): fsynced spool cost
    per offline tick, on-disk bytes per spooled snapshot (the spool
    sizing table's input), raw drain throughput over real HTTP, and
    backlog-to-live catch-up seconds at that ceiling (CI pins in
    tests/test_latency.py)."""
    from kube_gpu_stats_tpu.bench import measure_partition_drain

    drain = measure_partition_drain()
    if drain is not None:
        line["spill_spool_ms_per_frame"] = drain[
            "spill_spool_ms_per_frame"]
        line["spill_bytes_per_tick"] = drain["spill_bytes_per_tick"]
        line["partition_drain_frames_per_s"] = drain[
            "partition_drain_frames_per_s"]
        line["partition_catchup_s_200f"] = drain["partition_catchup_s"]


def _localfault_fields(line: dict) -> None:
    """Degraded-store tick cost (ISSUE 15): the per-tick price of the
    disk-backed store ops while their durability state machines are
    latched degraded, vs healthy fsync — the <10%-of-tick-budget CI
    pin lives in tests/test_latency.py."""
    from kube_gpu_stats_tpu.bench import measure_degraded_overhead

    degraded = measure_degraded_overhead()
    if degraded is not None:
        line["healthy_store_ms_per_tick"] = degraded[
            "healthy_store_ms_per_tick"]
        line["degraded_store_ms_per_tick"] = degraded[
            "degraded_store_ms_per_tick"]
        line["degraded_overhead_pct"] = degraded["degraded_overhead_pct"]


def _burst_fields(line: dict) -> None:
    """Burst-sampler cost figures (ISSUE 8): tick-path fold overhead as
    a percent of the 50 ms budget (the <2% CI pin, tests/test_latency),
    the achieved sampling rate, and the sampling thread's own CPU share
    (beside the loop, never inside it)."""
    from kube_gpu_stats_tpu.bench import measure_burst_overhead

    burst = measure_burst_overhead()
    if burst is not None:
        line["burst_overhead_pct"] = burst["burst_overhead_pct"]
        line["burst_fold_ms_per_tick"] = burst["burst_fold_ms_per_tick"]
        line["burst_samples_per_sec"] = burst["burst_samples_per_sec"]
        line["burst_thread_cpu_pct"] = burst["burst_thread_cpu_pct"]


def _cardinality_fields(line: dict) -> None:
    """Cardinality-admission cost (ISSUE 16): the accountant's
    bookkeeping per ingested series against the full ingest path's
    per-series cost (the <2% CI pin lives in tests/test_latency.py),
    and process RSS after a budgeted hub clamps a label bomb."""
    from kube_gpu_stats_tpu.bench import measure_cardinality_admission

    card = measure_cardinality_admission()
    if card is not None:
        line["cardinality_admission_ns_per_series"] = card[
            "cardinality_admission_ns_per_series"]
        line["cardinality_admission_overhead_pct"] = card[
            "cardinality_admission_overhead_pct"]
        line["hub_rss_mb_under_bomb"] = card["hub_rss_mb_under_bomb"]


def _host_fields(line: dict) -> None:
    """Host-signals collector cost (ISSUE 10): p50 of one full
    HostStats.read() over a realistic fixture tree — pool-thread cost
    per tick (off the tick budget by construction; the CI pin lives in
    tests/test_latency.py)."""
    from kube_gpu_stats_tpu.bench import measure_hoststats

    host = measure_hoststats()
    if host is not None:
        line["hoststats_read_ms_per_tick"] = host[
            "hoststats_read_ms_per_tick"]
        line["hoststats_read_p99_ms"] = host["hoststats_read_p99_ms"]


def _linkloc_fields(line: dict) -> None:
    """Interconnect-localization pass cost (ISSUE 19): median
    LinkLocalizer.observe wall time over an 8x8-torus fleet (256
    endpoint views per refresh, one verdict forming and clearing
    mid-run). Runs under the FleetLens lock on the refresh thread, so
    this is refresh latency — pinned against drift by bench_diff."""
    from kube_gpu_stats_tpu.bench import measure_fleet_localize

    loc = measure_fleet_localize()
    if loc is not None:
        line["fleet_localize_ms"] = loc["fleet_localize_ms"]


def _efficiency_fields(line: dict) -> None:
    """Waste-scoring pass cost (ISSUE 20): median EfficiencyLens.observe
    wall time over a 64-pod fold (EWMA scoring, verdict streaks, one
    idle reservation raising and clearing mid-run, one UNKNOWN pod).
    Runs under the FleetLens lock on the refresh thread, so this is
    refresh latency — pinned against drift by bench_diff."""
    from kube_gpu_stats_tpu.bench import measure_efficiency_score

    eff = measure_efficiency_score()
    if eff is not None:
        line["fleet_efficiency_ms_per_refresh"] = eff[
            "fleet_efficiency_ms_per_refresh"]


def _query_fields(line: dict) -> None:
    """Dashboard read-path figures (ISSUE 18): /query latency under 256
    keep-alive readers against a live-refreshing hub, the /metrics 304
    hit ratio under a steady generation, and the history ring's write
    cost + slab footprint (the CI pins live in tests/test_latency.py).

    Measured in a FRESH interpreter: this stage runs last, when the
    driver process carries heap and thread residue from every
    measurement before it (merge fleets, 10k-pusher storms, the label
    bomb), and that residue — not the serving path — showed up as a
    10x p99 inflation when measured in-process. A production hub never
    runs a bench suite first; the subprocess measures the hub."""
    import subprocess

    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "from kube_gpu_stats_tpu.bench import measure_query_serving\n"
             "import json\n"
             "print(json.dumps(measure_query_serving()))"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=300)
        query = json.loads(proc.stdout.strip() or "null")
    except (OSError, subprocess.SubprocessError, ValueError):
        query = None
    if query is not None:
        line["query_p50_ms_256readers"] = query["query_p50_ms_256readers"]
        line["query_p99_ms_256readers"] = query["query_p99_ms_256readers"]
        line["scrape_304_ratio"] = query["scrape_304_ratio"]
        line["history_write_ns_per_refresh"] = query[
            "history_write_ns_per_refresh"]
        line["history_rss_mb"] = query["history_rss_mb"]


def _merge_hub_fields(line: dict, measure_hub_merge) -> None:
    """Hub ingest/merge figures: the 64-worker shape is the BENCH
    trajectory's pinned number; 256 workers is the v5p-256
    one-target-per-chip-quad ceiling the north-star implies."""
    hub = measure_hub_merge()
    if hub is not None:
        line["hub_merge_64w_p50_ms"] = hub["p50_ms"]
        line["hub_merge_64w_cold_ms"] = hub["cold_ms"]
        line["hub_body_cache_hit_rate"] = hub["body_cache_hit_rate"]
        line["hub_parse_mb_per_s"] = hub["parse_mb_per_s"]
        line["hub_render_cache_hits"] = hub["render_cache_hits"]
        # Fleet-lens scoring cost per refresh at the 64w shape (ISSUE
        # 5): budget-pinned in tests/test_fleetlens.py — anomaly
        # baselines + SLO windows must stay a rounding error next to
        # the merge itself.
        line["fleet_score_ms_per_refresh"] = hub.get(
            "fleet_score_ms_per_refresh")
    hub256 = measure_hub_merge(workers=256, refreshes=5)
    if hub256 is not None:
        line["hub_merge_256w_p50_ms"] = hub256["p50_ms"]
        line["hub_merge_256w_cold_ms"] = hub256["cold_ms"]


def _quick() -> int:
    """Smoke bench: simulated harness at reduced ticks + the 64w hub
    merge, skipping every real-chip probe. One JSON line, same field
    names as the full run plus quick: true so a smoke number can never
    be mistaken for a BENCH artifact."""
    from kube_gpu_stats_tpu.bench import (measure_hub_merge,
                                          run_latency_harness)

    with tempfile.TemporaryDirectory() as tmp:
        result = run_latency_harness(
            tmp, num_chips=8, ticks=15, rpc_delay=0.010, warmup=3,
            subprocess_server=True,
        )
    p50 = result["p50_ms"]
    line = {
        "metric": f"poll_tick_p50_ms_{result['chips']}chip_{result['mode']}",
        "value": round(p50, 3),
        "unit": "ms",
        "vs_baseline": round(BUDGET_MS / p50, 3) if p50 > 0 else None,
        "p99_ms": round(result["p99_ms"], 3),
        "scrape_p50_ms": round(result.get("scrape_p50_ms", 0.0), 3),
        "gc_collections": result.get("gc_collections"),
        "gc_max_pause_ms": result.get("gc_max_pause_ms"),
        # Tick-plan + batched-RPC pins (ISSUE 3): snapshot objects built
        # per tick (plan slots re-emit unchanged values) and RPCs per
        # tick (batched mode: one per port).
        "tick_alloc_objects_per_tick": result.get(
            "tick_alloc_objects_per_tick"),
        "rpc_calls_per_tick": result.get("rpc_calls_per_tick"),
        # Flight-recorder cost pins (ISSUE 4): spans recorded per tick
        # and the measured per-span overhead budget.
        "tick_spans_per_tick": result.get("tick_spans_per_tick"),
        "trace_overhead_ns_per_span": result.get(
            "trace_overhead_ns_per_span"),
        "mode": result["mode"],
        "chips": result["chips"],
        "quick": True,
    }
    hub = measure_hub_merge(refreshes=5)
    if hub is not None:
        line["hub_merge_64w_p50_ms"] = hub["p50_ms"]
        line["hub_merge_64w_cold_ms"] = hub["cold_ms"]
        line["hub_body_cache_hit_rate"] = hub["body_cache_hit_rate"]
        line["fleet_score_ms_per_refresh"] = hub.get(
            "fleet_score_ms_per_refresh")
    _delta_fields(line, quick=True)
    _egress_fields(line)
    _localfault_fields(line)
    _burst_fields(line)
    _host_fields(line)
    _cardinality_fields(line)
    _linkloc_fields(line)
    _efficiency_fields(line)
    _query_fields(line)
    print(json.dumps(line))
    sys.stdout.flush()
    os._exit(0)


def main() -> int:
    from kube_gpu_stats_tpu.bench import (measure_hub_merge,
                                          run_latency_harness,
                                          try_embedded_harness,
                                          try_real_harness)

    if "--quick" in sys.argv[1:]:
        return _quick()

    result, probe = try_real_harness(ticks=50, warmup=5)
    if result is None:
        # No external metric surface (the probe says exactly why): the
        # embedded in-process collector is the remaining real-chip path.
        result = try_embedded_harness(probe, ticks=50, warmup=5)
    simulated = None
    if result is None:
        with tempfile.TemporaryDirectory() as tmp:
            simulated = run_latency_harness(
                tmp, num_chips=8, ticks=50, rpc_delay=0.010, warmup=5,
                subprocess_server=True,
            )
        # Round-end real-mode retry: one probe per run lost BENCH_r04's
        # real numbers when the chip tunnel recovered between bench
        # start and round end (round-4 verdict, weak 1). The simulated
        # run above takes minutes — long enough for a tunnel to come
        # back — so re-attempt; a still-down tunnel costs one more
        # bounded probe. On success the real measurement becomes the
        # headline and the simulated section ships alongside it.
        retry_probe: dict = {}
        result, retry_probe = try_real_harness(ticks=50, warmup=5)
        if result is None:
            result = try_embedded_harness(retry_probe, ticks=50, warmup=5)
        probe["round_end_retry"] = retry_probe
        if result is None:
            result = simulated
            simulated = None
    p50 = result["p50_ms"]
    line = {
        "metric": f"poll_tick_p50_ms_{result['chips']}chip_{result['mode']}",
        "value": round(p50, 3),
        "unit": "ms",
        "vs_baseline": round(BUDGET_MS / p50, 3) if p50 > 0 else None,
        "p90_ms": round(result["p90_ms"], 3),
        "p99_ms": round(result["p99_ms"], 3),
        "metrics_per_sec_per_chip": round(result["metrics_per_chip"], 1),
        "max_hz": round(result["max_hz"], 1),
        # End-to-end HTTP scrape (render + gzip-negotiation + socket) over
        # the same snapshots — the render half of the north-star metric.
        "scrape_p50_ms": round(result.get("scrape_p50_ms", 0.0), 3),
        "scrape_p99_ms": round(result.get("scrape_p99_ms", 0.0), 3),
        # GC probe (BENCH_r05 p99 pin): collections observed inside the
        # measured window and the worst single pause. With the
        # post-warmup freeze these should stay near 0 / sub-ms; a p99
        # excursion with gc_max_pause_ms ~0 is NOT the collector.
        "gc_collections": result.get("gc_collections"),
        "gc_max_pause_ms": result.get("gc_max_pause_ms"),
        # Tick-plan + batched-RPC pins (ISSUE 3): snapshot objects built
        # per tick (plan slots re-emit unchanged values; the rest of the
        # snapshot is reused) and RPCs the runtime fetch issues per tick
        # (batched mode: one per port; 0 families batched = per-metric
        # burst fallback).
        "tick_alloc_objects_per_tick": result.get(
            "tick_alloc_objects_per_tick"),
        "tick_series_per_tick": result.get("tick_series_per_tick"),
        "rpc_calls_per_tick": result.get("rpc_calls_per_tick"),
        "rpc_batched_families": result.get("rpc_batched_families"),
        # Flight-recorder cost pins (ISSUE 4): spans recorded per tick
        # (phases + per-device/per-port aux) and the measured per-span
        # overhead — tracing ships ON by default, so its price is a
        # north-star input, budget-pinned in tests/test_latency.py.
        "tick_spans_per_tick": result.get("tick_spans_per_tick"),
        "trace_overhead_ns_per_span": result.get(
            "trace_overhead_ns_per_span"),
        "mode": result["mode"],
        "path": result.get("path", "fake-grpc"),
        "chips": result["chips"],
        # Machine-checked evidence of why mode is (or isn't) real —
        # present in every run so a fallback explains itself.
        "real_probe": probe,
    }
    if "device_kind" in result:
        line["device_kind"] = result["device_kind"]
    for key in ("workload_steps_per_s_during_bench",
                "workload_busy_fraction_during_bench",
                "workload_mfu_pct_during_bench",
                "mfu_sweep"):
        if key in result and result[key] is not None:
            line[key] = result[key]
    # Slice-aggregation cost at the v5p-256 shape (64 workers x 4 chips,
    # full labels + ICI links): median hub refresh wall time. An extra
    # datum — None/omitted on failure, never a bench failure.
    if simulated is not None:
        # Both modes in one artifact: the retry found a live chip after
        # the simulated harness already ran — ship its figures too so
        # the regression pin (simulated numbers) survives a real round.
        line["simulated"] = {
            "p50_ms": round(simulated["p50_ms"], 3),
            "p90_ms": round(simulated["p90_ms"], 3),
            "p99_ms": round(simulated["p99_ms"], 3),
            "scrape_p50_ms": round(simulated.get("scrape_p50_ms", 0.0), 3),
            "chips": simulated["chips"],
            "metrics_per_sec_per_chip": round(
                simulated["metrics_per_chip"], 1),
            "gc_collections": simulated.get("gc_collections"),
            "gc_max_pause_ms": simulated.get("gc_max_pause_ms"),
        }
    _merge_hub_fields(line, measure_hub_merge)
    _delta_fields(line)
    _egress_fields(line)
    _localfault_fields(line)
    _burst_fields(line)
    _host_fields(line)
    _cardinality_fields(line)
    _linkloc_fields(line)
    _efficiency_fields(line)
    _query_fields(line)
    print(json.dumps(line))
    # Guarantee exit: a wedged chip tunnel can leave a daemon thread (or
    # PJRT atexit hook) blocked in native code; the JSON line is already
    # out, and the driver must get its exit code, not a hang.
    sys.stdout.flush()
    os._exit(0)
    return 0


if __name__ == "__main__":
    sys.exit(main())
